"""Device-resident neighbor rebuild: cell-list parity vs the numpy FPIS
reference across PBC edge cases, in-place graph refresh exactness, the
device-resident DeviceMD loop (single program, no host callbacks, flat
compile count across rebuilds), and overflow fallback robustness."""

import numpy as np
import pytest

from distmlip_tpu import geometry
from distmlip_tpu.neighbors import neighbor_list_numpy
from distmlip_tpu.neighbors.device import (build_cell_list_spec,
                                           build_packed_spec,
                                           device_neighbor_list,
                                           device_packed_neighbor_list)

pytestmark = pytest.mark.device_neighbors


def _ref_pairs(cart, lattice, pbc, r):
    nl = neighbor_list_numpy(cart, lattice, pbc, r)
    return set(zip(nl.src.tolist(), nl.dst.tolist(),
                   map(tuple, nl.offsets.tolist())))


def _dev_pairs(cart, lattice, pbc, r, n_cap=None, e_cap=8192):
    n = len(cart)
    n_cap = n_cap or n
    pos = np.zeros((n_cap, 3), np.float32)
    pos[:n] = cart
    static, arrays = build_cell_list_spec(
        lattice, pbc, r, n, n_cap, e_cap, positions=cart)
    src, dst, off, n_edges, overflow = device_neighbor_list(
        static, arrays, pos)
    assert not bool(overflow)
    ne = int(n_edges)
    src = np.asarray(src)[:ne]
    dst = np.asarray(dst)[:ne]
    off = np.asarray(off)[:ne]
    # graph contract: dst (the aggregation center) globally nondecreasing
    assert np.all(np.diff(dst) >= 0)
    return set(zip(src.tolist(), dst.tolist(), map(tuple, off.tolist())))


# ---------------------------------------------------------------------------
# parity vs neighbor_list_numpy (exact pair sets) — PBC edge-case suite
# ---------------------------------------------------------------------------


@pytest.mark.tier1
def test_parity_cubic(rng):
    lattice = np.eye(3) * 8.0
    cart = rng.random((40, 3)) @ lattice
    assert _ref_pairs(cart, lattice, [1, 1, 1], 3.0) == \
        _dev_pairs(cart, lattice, [1, 1, 1], 3.0)


@pytest.mark.tier1
def test_parity_triclinic(rng):
    """Strongly skewed (triclinic) lattice: plane-spacing grid sizing must
    stay exact under skew."""
    lattice = np.array([[8.0, 0, 0], [2.5, 7.0, 0], [1.5, -2.0, 6.5]])
    cart = rng.random((30, 3)) @ lattice
    assert _ref_pairs(cart, lattice, [1, 1, 1], 3.2) == \
        _dev_pairs(cart, lattice, [1, 1, 1], 3.2)


@pytest.mark.tier1
def test_parity_tiny_cell_multi_image(rng):
    """Cutoff > box: multi-image pairs (multi-wrap stencil reach) and an
    atom neighboring its own periodic images."""
    lattice = np.eye(3) * 2.0
    cart = np.array([[0.5, 0.5, 0.5], [1.2, 0.4, 1.7]])
    assert _ref_pairs(cart, lattice, [1, 1, 1], 2.9) == \
        _dev_pairs(cart, lattice, [1, 1, 1], 2.9)


@pytest.mark.tier1
def test_parity_one_atom():
    lattice = np.eye(3) * 2.0
    cart = np.array([[0.5, 0.5, 0.5]])
    pairs = _dev_pairs(cart, lattice, [1, 1, 1], 2.9)
    assert pairs == _ref_pairs(cart, lattice, [1, 1, 1], 2.9)
    assert len(pairs) > 0  # self-image neighbors exist


def test_parity_partial_pbc_unwrapped(rng):
    """Non-periodic axis + unwrapped (translated) inputs: offsets must be
    reported relative to the input frame, no wrap on the open axis."""
    lattice = np.array([[8.0, 0, 0], [2.5, 7.0, 0], [1.5, -2.0, 6.5]])
    cart = rng.random((30, 3)) @ lattice
    shift = rng.integers(-3, 4, (30, 3)) @ lattice
    moved = cart + shift
    assert _ref_pairs(moved, lattice, [1, 1, 0], 3.0) == \
        _dev_pairs(moved, lattice, [1, 1, 0], 3.0)


def test_parity_padded_rows(rng):
    """Padded node rows (n_cap > n_atoms) must contribute no edges."""
    lattice = np.eye(3) * 7.0
    cart = rng.random((25, 3)) @ lattice
    assert _ref_pairs(cart, lattice, [1, 1, 1], 2.8) == \
        _dev_pairs(cart, lattice, [1, 1, 1], 2.8, n_cap=64)


def test_parity_random_sweep():
    for seed in range(5):
        r = np.random.default_rng(seed)
        n = int(r.integers(5, 70))
        box = float(r.uniform(3.0, 10.0))
        lattice = np.eye(3) * box
        lattice[0, 1] = r.uniform(-0.3, 0.3) * box
        lattice[1, 2] = r.uniform(-0.3, 0.3) * box
        cart = r.random((n, 3)) @ lattice
        cutoff = float(r.uniform(1.5, 3.5))
        assert _ref_pairs(cart, lattice, [1, 1, 1], cutoff) == \
            _dev_pairs(cart, lattice, [1, 1, 1], cutoff, e_cap=16384), seed


@pytest.mark.tier1
def test_packed_parity(rng):
    """Block-diagonal packed batch: every block's device edges must equal
    its own numpy reference (Cartesian-baked offsets, block-sorted dst)."""
    structs = [
        (rng.random((12, 3)) @ (np.eye(3) * 6.0), np.eye(3) * 6.0,
         [1, 1, 1]),
        (rng.random((7, 3)) @ np.array([[5.0, 0, 0], [1.2, 4.5, 0],
                                        [0, 0.8, 4.8]]),
         np.array([[5.0, 0, 0], [1.2, 4.5, 0], [0, 0.8, 4.8]]), [1, 1, 1]),
        (np.array([[0.5, 0.5, 0.5]]), np.eye(3) * 2.0, [1, 1, 1]),
    ]
    r = 2.7
    n_atoms = [len(c) for c, *_ in structs]
    node_off = np.concatenate([[0], np.cumsum(n_atoms)])
    n_cap, e_cap = 64, 4096
    pos = np.zeros((n_cap, 3), np.float32)
    for b, (c, *_) in enumerate(structs):
        pos[node_off[b]:node_off[b + 1]] = c
    static, arrays = build_packed_spec(
        [s[1] for s in structs], [s[2] for s in structs], n_atoms, node_off,
        r, n_cap, e_cap)
    src, dst, off, n_edges, overflow = device_packed_neighbor_list(
        static, arrays, pos)
    assert not bool(overflow)
    ne = int(n_edges)
    src, dst, off = (np.asarray(src)[:ne], np.asarray(dst)[:ne],
                     np.asarray(off)[:ne])
    assert np.all(np.diff(dst) >= 0)
    for b, (cart, lattice, pbc) in enumerate(structs):
        nl = neighbor_list_numpy(cart, lattice, pbc, r)
        ref = sorted(zip(nl.src.tolist(), nl.dst.tolist(),
                         map(tuple, (nl.offsets @ lattice).round(3))))
        sel = (dst >= node_off[b]) & (dst < node_off[b + 1])
        got = sorted(zip((src[sel] - node_off[b]).tolist(),
                         (dst[sel] - node_off[b]).tolist(),
                         map(tuple, off[sel].astype(np.float64).round(3))))
        assert len(ref) == len(got), b
        for a, g in zip(ref, got):
            assert a[0] == g[0] and a[1] == g[1], b
            np.testing.assert_allclose(a[2], g[2], atol=2e-3)


# ---------------------------------------------------------------------------
# overflow flags
# ---------------------------------------------------------------------------


@pytest.mark.tier1
def test_edge_overflow_flag(rng):
    lattice = np.eye(3) * 6.0
    cart = rng.random((30, 3)) @ lattice
    static, arrays = build_cell_list_spec(
        lattice, [1, 1, 1], 3.0, 30, 30, 8, positions=cart)  # e_cap=8: tiny
    src, dst, off, n_edges, overflow = device_neighbor_list(
        static, arrays, cart.astype(np.float32))
    assert bool(overflow)
    # the COUNT still reports the true need so the host can grow the cap
    assert int(n_edges) == len(_ref_pairs(cart, lattice, [1, 1, 1], 3.0))


@pytest.mark.tier1
def test_cell_overflow_flag(rng):
    lattice = np.eye(3) * 6.0
    cart = rng.random((30, 3)) @ lattice
    static, arrays = build_cell_list_spec(
        lattice, [1, 1, 1], 3.0, 30, 30, 8192, positions=cart, cell_cap=1)
    *_rest, overflow = device_neighbor_list(
        static, arrays, cart.astype(np.float32))
    assert bool(overflow)


# ---------------------------------------------------------------------------
# in-place refresh: padding contract + exactness through a potential
# ---------------------------------------------------------------------------


def _lj_setup(rng, reps=(3, 3, 3), skin=0.5, cutoff=3.0):
    from distmlip_tpu.calculators import Atoms

    unit = np.array([[0, 0, 0], [0.5, 0.5, 0], [0.5, 0, 0.5],
                     [0, 0.5, 0.5]])
    frac, lattice = geometry.make_supercell(unit, np.eye(3) * 3.8, reps)
    cart = geometry.frac_to_cart(frac, lattice) + rng.normal(
        0, 0.03, (len(frac), 3))
    atoms = Atoms(numbers=np.full(len(cart), 14), positions=cart,
                  cell=lattice)
    from distmlip_tpu.models import PairConfig, PairPotential

    model = PairPotential(PairConfig(cutoff=cutoff, kind="lj"))
    params = {"eps": np.float32(0.05), "sigma": np.float32(2.0)}
    return atoms, model, params


@pytest.mark.tier1
def test_refresh_contract_and_exactness(rng):
    """refresh_edges must re-establish the full padding contract and the
    refreshed graph must reproduce a from-scratch host rebuild's energy/
    forces/stress to fp32 roundoff."""
    import jax.numpy as jnp

    from distmlip_tpu.neighbors import neighbor_list_numpy as nln
    from distmlip_tpu.parallel import make_potential_fn
    from distmlip_tpu.partition import (CapacityPolicy, build_plan,
                                        build_partitioned_graph,
                                        device_refresh_graph)

    atoms, model, params = _lj_setup(rng)
    r = 3.0
    caps = CapacityPolicy()
    nl = nln(atoms.positions, atoms.cell, atoms.pbc, r)
    plan = build_plan(nl, atoms.cell, atoms.pbc, 1, r)
    graph, host = build_partitioned_graph(
        plan, nl, np.full(len(atoms), 14, np.int32), atoms.cell, caps=caps)
    static, arrays = build_cell_list_spec(
        atoms.cell, atoms.pbc, r, len(atoms), graph.n_cap, graph.e_cap,
        positions=atoms.positions)
    drift = atoms.positions + rng.normal(0, 0.25, atoms.positions.shape)
    pos = jnp.asarray(host.scatter_global(drift.astype(np.float32),
                                          graph.n_cap))
    graph2, n_edges, overflow = device_refresh_graph(
        static, arrays, graph, pos)
    assert not bool(overflow)
    ne = int(n_edges)
    edge_dst = np.asarray(graph2.edge_dst[0])
    edge_mask = np.asarray(graph2.edge_mask[0])
    assert edge_mask.sum() == ne
    assert np.all(np.diff(edge_dst) >= 0)          # globally nondecreasing
    assert np.all(edge_dst[ne:] == edge_dst[ne - 1])  # repeat-last padding
    assert np.all(np.asarray(graph2.edge_src[0])[ne:] == 0)

    pot = make_potential_fn(model.energy_fn, None)
    out_dev = pot(params, graph2, pos)
    nl2 = nln(drift, atoms.cell, atoms.pbc, r)
    plan2 = build_plan(nl2, atoms.cell, atoms.pbc, 1, r)
    graph3, host3 = build_partitioned_graph(
        plan2, nl2, np.full(len(atoms), 14, np.int32), atoms.cell, caps=caps)
    out_host = pot(params, graph3, graph3.positions)
    assert abs(float(out_dev["energy"]) - float(out_host["energy"])) < 1e-5
    f_dev = host.gather_owned(np.asarray(out_dev["forces"]), len(atoms))
    f_host = host3.gather_owned(np.asarray(out_host["forces"]), len(atoms))
    np.testing.assert_allclose(f_dev, f_host, atol=1e-5)


def test_refresh_rejects_unsupported_graphs(rng):
    """Bond graphs and frontier-split layouts must refuse the in-place
    swap loudly (their auxiliary arrays would go stale)."""
    import jax.numpy as jnp

    from distmlip_tpu.neighbors import neighbor_list_numpy as nln
    from distmlip_tpu.partition import (build_plan, build_partitioned_graph,
                                        refresh_edges)

    atoms, *_ = _lj_setup(rng)
    nl = nln(atoms.positions, atoms.cell, atoms.pbc, 3.0, bond_r=2.0)
    plan = build_plan(nl, atoms.cell, atoms.pbc, 1, 3.0, 2.0,
                      use_bond_graph=True)
    graph, _host = build_partitioned_graph(
        plan, nl, np.full(len(atoms), 14, np.int32), atoms.cell)
    z = jnp.zeros((graph.e_cap,), jnp.int32)
    with pytest.raises(ValueError, match="bond"):
        refresh_edges(graph, z, z, jnp.zeros((graph.e_cap, 3)), 0)


# ---------------------------------------------------------------------------
# DistPotential / BatchedPotential integration
# ---------------------------------------------------------------------------


@pytest.mark.tier1
def test_distpotential_device_refresh_parity(rng):
    """Skin-cache invalidations on a single-partition potential must be
    served ON DEVICE and match the host-rebuild potential step for step."""
    from distmlip_tpu.calculators import DistPotential

    atoms, model, params = _lj_setup(rng)
    pot_dev = DistPotential(model, params, num_partitions=1, skin=0.5)
    pot_host = DistPotential(model, params, num_partitions=1, skin=0.5,
                             device_rebuild=False)
    a1, a2 = atoms.copy(), atoms.copy()
    for _ in range(4):
        r1 = pot_dev.calculate(a1)
        r2 = pot_host.calculate(a2)
        assert abs(r1["energy"] - r2["energy"]) < 1e-5
        np.testing.assert_allclose(r1["forces"], r2["forces"], atol=1e-4)
        np.testing.assert_allclose(r1["stress"], r2["stress"], atol=1e-5)
        step = rng.normal(0, 0.12, a1.positions.shape)
        a1.positions = a1.positions + step
        a2.positions = a2.positions + step
    assert pot_dev.rebuild_on_device_count >= 2
    assert pot_host.rebuild_on_device_count == 0
    # the device refresh leaves no host FPIS time in the phase breakdown
    assert pot_dev.last_timings["neighbor_s"] < 0.005
    assert "rebuild_s" in pot_dev.last_timings


def test_env_kill_switch(rng, monkeypatch):
    from distmlip_tpu.calculators import DistPotential

    monkeypatch.setenv("DISTMLIP_DEVICE_REBUILD", "0")
    atoms, model, params = _lj_setup(rng)
    pot = DistPotential(model, params, num_partitions=1, skin=0.5)
    a = atoms.copy()
    for _ in range(3):
        pot.calculate(a)
        a.positions = a.positions + rng.normal(0, 0.2, a.positions.shape)
    assert pot.rebuild_on_device_count == 0
    assert pot.rebuild_count >= 2  # host rebuilds served the invalidations


@pytest.mark.tier1
def test_batched_device_refresh_parity(rng):
    """Packed-batch invalidations (same structure list, drifted positions)
    refresh on device and match a rebuild-every-call reference, with zero
    extra executables."""
    from distmlip_tpu.calculators import Atoms, BatchedPotential

    atoms, model, params = _lj_setup(rng, reps=(2, 2, 2))
    tiny = Atoms(numbers=np.array([14]),
                 positions=np.array([[0.5, 0.5, 0.5]]), cell=np.eye(3) * 2.5)
    structs = [atoms, tiny]
    bp = BatchedPotential(model, params, skin=0.4)
    bp_ref = BatchedPotential(model, params, skin=0.0, device_rebuild=False)
    bp.calculate(structs)
    compiles_before = bp.compile_count
    for _ in range(3):
        for a in structs:
            a.positions = a.positions + rng.normal(0, 0.15,
                                                   a.positions.shape)
        r1 = bp.calculate(structs)
        r2 = bp_ref.calculate(structs)
        for b in range(len(structs)):
            assert abs(r1[b]["energy"] - r2[b]["energy"]) < 2e-5
            np.testing.assert_allclose(r1[b]["forces"], r2[b]["forces"],
                                       atol=1e-4)
    assert bp.rebuild_on_device_count >= 2
    assert bp.compile_count == compiles_before  # refresh never recompiles


# ---------------------------------------------------------------------------
# DeviceMD: device-resident trajectories
# ---------------------------------------------------------------------------


@pytest.mark.tier1
def test_device_md_in_loop_rebuild_matches_host(rng):
    """A trajectory whose skin invalidations are rebuilt IN-LOOP on device
    must match the host-rebuild DeviceMD trajectory, complete all steps in
    ONE chunk dispatch, and never grow the stepper's executable cache."""
    from distmlip_tpu.calculators import DeviceMD, DistPotential

    atoms, model, params = _lj_setup(rng)
    atoms.set_maxwell_boltzmann_velocities(300.0,
                                           rng=np.random.default_rng(7))
    a_dev, a_host = atoms.copy(), atoms.copy()

    pot_dev = DistPotential(model, params, num_partitions=1, skin=0.4)
    md_dev = DeviceMD(pot_dev, a_dev, timestep=1.0)
    assert md_dev.device_rebuild
    md_dev.run(40)

    pot_host = DistPotential(model, params, num_partitions=1, skin=0.4,
                             device_rebuild=False)
    md_host = DeviceMD(pot_host, a_host, timestep=1.0,
                       device_rebuild=False)
    md_host.run(40)

    assert md_dev.steps_done == 40 and md_host.steps_done == 40
    assert md_dev.rebuilds_on_device >= 1     # the skin DID fire in-loop
    assert md_host.rebuilds >= 2              # ... and on host in the A/B
    np.testing.assert_allclose(a_dev.positions, a_host.positions, atol=1e-3)
    np.testing.assert_allclose(a_dev.velocities, a_host.velocities,
                               atol=1e-3)
    # compile count stays flat across rebuilds: one chunk executable
    assert md_dev._dev_stepper._cache_size() == 1


@pytest.mark.tier1
def test_device_md_chunk_is_single_device_program(rng):
    """Trace-level acceptance: a chunk containing skin-triggered rebuilds
    lowers to one device program — the rebuild (sort-based binning) sits
    INSIDE the while loop and there is no host callback anywhere."""
    import jax
    import jax.numpy as jnp

    from distmlip_tpu.calculators import DeviceMD, DistPotential
    from distmlip_tpu.parallel.audit import (count_host_callbacks,
                                             count_primitives)

    atoms, model, params = _lj_setup(rng)
    pot = DistPotential(model, params, num_partitions=1, skin=0.4)
    md = DeviceMD(pot, atoms, timestep=1.0)
    graph, host, positions = pot._prepare(atoms)
    md._ensure_spec(graph)
    dtype = np.asarray(graph.lattice).dtype
    ref = host.scatter_global(pot._cache[3].astype(dtype), graph.n_cap)
    vel = host.scatter_global(atoms.velocities.astype(dtype), graph.n_cap)
    masses = host.scatter_global(atoms.masses.astype(dtype), graph.n_cap,
                                 fill=1.0)
    jaxpr = jax.make_jaxpr(md._dev_stepper)(
        pot.params, graph, positions, ref, vel, masses, jnp.int32(8),
        jnp.float32(0.0), jnp.float32(0.0))
    assert not count_host_callbacks(jaxpr), count_host_callbacks(jaxpr)
    prims = count_primitives(jaxpr, {"while", "sort"})
    assert prims["while"] >= 1   # the chunk loop
    assert prims["sort"] >= 1    # the in-loop cell-list binning


def test_device_md_overflow_falls_back_and_continues(rng):
    """A device-capacity bust mid-trajectory must fall back to the host
    rebuild with grown caps, count the overflow, and preserve trajectory
    continuity (all steps complete, same physics as the clean run)."""
    from distmlip_tpu.calculators import DeviceMD, DistPotential

    atoms, model, params = _lj_setup(rng)
    atoms.set_maxwell_boltzmann_velocities(300.0,
                                           rng=np.random.default_rng(9))
    a_ovf, a_clean = atoms.copy(), atoms.copy()

    pot_o = DistPotential(model, params, num_partitions=1, skin=0.4,
                          device_rebuild=False)  # DeviceMD drives the spec
    # explicit True overrides the potential's opt-out ("auto" would inherit)
    md_o = DeviceMD(pot_o, a_ovf, timestep=1.0, device_rebuild=True,
                    cell_capacity=1)
    md_o.run(40)
    assert md_o.steps_done == 40
    assert md_o.rebuild_overflows >= 1
    # the fallback grew the cell capacity, so later rebuilds succeeded
    assert md_o._cell_cap_floor > 1 or md_o.rebuilds_on_device == 0

    pot_c = DistPotential(model, params, num_partitions=1, skin=0.4,
                          device_rebuild=False)
    md_c = DeviceMD(pot_c, a_clean, timestep=1.0)
    md_c.run(40)
    np.testing.assert_allclose(a_ovf.positions, a_clean.positions,
                               atol=2e-3)
    # energy drift unchanged: both runs end at the same total energy scale
    e_o = md_o.results["energy"] + md_o.results["kinetic"]
    e_c = md_c.results["energy"] + md_c.results["kinetic"]
    assert abs(e_o - e_c) < 5e-3


def test_device_md_multi_partition_keeps_host_path(rng):
    """P > 1 potentials cannot refresh in place — DeviceMD must silently
    keep the host-rebuild chunk loop (no behavior change)."""
    from distmlip_tpu.calculators import DeviceMD, DistPotential

    atoms, model, params = _lj_setup(rng)
    pot = DistPotential(model, params, num_partitions=2, skin=0.5)
    md = DeviceMD(pot, atoms, timestep=1.0)
    assert not md.device_rebuild
    md.run(10)
    assert md.steps_done == 10
    assert md.rebuilds_on_device == 0


# ---------------------------------------------------------------------------
# telemetry: rebuild counters flow to records and the report
# ---------------------------------------------------------------------------


def test_device_md_rebuild_telemetry(rng):
    from distmlip_tpu.calculators import DeviceMD, DistPotential
    from distmlip_tpu.telemetry import Telemetry
    from distmlip_tpu.telemetry.sinks import TelemetrySink

    class Capture(TelemetrySink):
        def __init__(self):
            self.records = []

        def emit(self, record):
            self.records.append(record)

    atoms, model, params = _lj_setup(rng)
    cap = Capture()
    pot = DistPotential(model, params, num_partitions=1, skin=0.4,
                        telemetry=Telemetry([cap]))
    md = DeviceMD(pot, atoms, timestep=1.0)
    md.run(40)
    chunks = [r for r in cap.records if r.kind == "md_chunk"]
    assert chunks
    assert sum(r.rebuild_on_device for r in chunks) == md.rebuilds_on_device
    assert sum(r.rebuild_count for r in chunks) >= md.rebuilds_on_device


def test_report_rebuild_line_and_host_dominant_anomaly():
    from distmlip_tpu.telemetry.record import StepRecord
    from distmlip_tpu.telemetry.report import aggregate

    recs = [
        StepRecord(step=1, kind="md_chunk", rebuild=True, rebuild_count=4,
                   rebuild_on_device=1, rebuild_overflow_count=2,
                   timings={"total_s": 1.0, "rebuild_s": 0.01}),
        StepRecord(step=2, kind="md_chunk", rebuild=True, rebuild_count=2,
                   rebuild_on_device=1, rebuild_overflow_count=2,
                   timings={"total_s": 1.0}),
    ]
    rep = aggregate(recs)
    assert rep.counters["rebuilds_total"] == 6
    assert rep.counters["rebuilds_on_device"] == 2
    assert rep.counters["rebuild_overflows"] == 2
    text = rep.render()
    assert "rebuilds: total=6 on_device=2 host=4" in text
    assert any(a.kind == "host_rebuild_dominant" for a in rep.anomalies)
    # a device-dominant run must NOT flag
    ok = [StepRecord(step=1, kind="md_chunk", rebuild=True, rebuild_count=5,
                     rebuild_on_device=5, timings={"total_s": 1.0})]
    assert not [a for a in aggregate(ok).anomalies
                if a.kind == "host_rebuild_dominant"]
    # legacy records (no rebuild_count) still fold into the total
    legacy = [StepRecord(step=1, rebuild=True, timings={"total_s": 1.0})]
    assert aggregate(legacy).counters["rebuilds_total"] == 1
