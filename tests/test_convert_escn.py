"""MAPPINGS["escn"] golden contract: a float64, explicit-loop torch oracle
implementing the fairchem eSCNMDBackbone parameterization (key names and
shapes as a real UMA-family ``state_dict()``), converted through
``from_torch("escn", ...)`` and evaluated by ESCNMD — energies and forces
must agree to <= 1e-6 (both sides float64). The oracle is written
independently of the JAX model (plain tensor ops, explicit per-l/per-m
loops, torch autograd forces); the shared ingredient is the derived Jd
table, which tests/test_so3_e3nn.py pins by property and an upstream-
convention anchor.

Covers VERDICT r3 next-round item 3: zero-unmapped conversion of a
UMA-shaped synthetic dict + oracle parity, closing the last model family
without a converter (reference implementations/uma/escn_md.py:559-569).
"""

import numpy as np
import pytest
import torch

import jax

from distmlip_tpu.models import ESCNMD, ESCNMDConfig
from distmlip_tpu.models.convert import from_torch
from distmlip_tpu.ops.so3_e3nn import CoeffLayout, jd_np

pytestmark = pytest.mark.slow

torch.manual_seed(0)

Z, C, H, CE, DB, NL = 5, 8, 8, 6, 10, 2
LMAX, MMAX = 3, 2
CUT, AVG = 3.5, 9.0
NQ, NS, ND = 7, 4, 3
CFG = ESCNMDConfig(
    max_num_elements=Z, sphere_channels=C, lmax=LMAX, mmax=MMAX,
    num_layers=NL, hidden_channels=H, edge_channels=CE,
    num_distance_basis=DB, cutoff=CUT, avg_degree=AVG,
    num_charges=NQ, charge_min=-3, num_spins=NS, num_datasets=ND,
    edge_chunk=0,
)
DX = DB + 2 * CE
LAY = CoeffLayout(LMAX, MMAX)


def _lin(sd, name, d_out, d_in, bias=True):
    sd[name + ".weight"] = torch.randn(d_out, d_in, dtype=torch.float64) / np.sqrt(d_in)
    if bias:
        sd[name + ".bias"] = torch.randn(d_out, dtype=torch.float64) * 0.1


def _rad(sd, prefix, d_in, d_hidden, d_out):
    _lin(sd, prefix + ".net.0", d_hidden, d_in)
    sd[prefix + ".net.1.weight"] = 1.0 + 0.1 * torch.randn(d_hidden, dtype=torch.float64)
    sd[prefix + ".net.1.bias"] = 0.1 * torch.randn(d_hidden, dtype=torch.float64)
    _lin(sd, prefix + ".net.3", d_out, d_hidden)


def synthetic_escn_state_dict():
    """A UMA/eSCNMD-shaped state dict (fairchem key names, random values)."""
    sd = {}
    sd["backbone.sphere_embedding.weight"] = torch.randn(Z, C, dtype=torch.float64)
    sd["backbone.source_embedding.weight"] = torch.randn(Z, CE, dtype=torch.float64)
    sd["backbone.target_embedding.weight"] = torch.randn(Z, CE, dtype=torch.float64)
    sd["backbone.csd_embedding.charge_embedding.weight"] = torch.randn(NQ, C, dtype=torch.float64)
    sd["backbone.csd_embedding.spin_embedding.weight"] = torch.randn(NS, C, dtype=torch.float64)
    sd["backbone.csd_embedding.dataset_embedding.weight"] = torch.randn(ND, C, dtype=torch.float64)
    _lin(sd, "backbone.csd_embedding.mix_csd", C, 3 * C)
    sd["backbone.distance_expansion.offset"] = torch.linspace(0.0, CUT, DB, dtype=torch.float64)
    _rad(sd, "backbone.edge_degree_embedding.rad_func", DX, CE, (LMAX + 1) * C)
    for i in range(NL):
        bp = f"backbone.blocks.{i}"
        sd[bp + ".norm_1.affine_weight"] = 1.0 + 0.1 * torch.randn(LMAX + 1, C, dtype=torch.float64)
        # so2_conv_1: in 2C, out H, extra gate scalars LMAX*H
        rad_len = sum(LAY.m_size(m) for m in range(MMAX + 1)) * 2 * C
        _rad(sd, bp + ".so2_conv_1.rad_func", DX, CE, rad_len)
        m0_in, m0_out = LAY.m_size(0) * 2 * C, LAY.m_size(0) * H + LMAX * H
        _lin(sd, bp + ".so2_conv_1.fc_m0", m0_out, m0_in)
        for m in range(1, MMAX + 1):
            nl = LAY.m_size(m)
            _lin(sd, f"{bp}.so2_conv_1.so2_m_conv.{m - 1}.fc",
                 2 * nl * H, nl * 2 * C, bias=False)
        # so2_conv_2: in H, out C, internal weights
        _lin(sd, bp + ".so2_conv_2.fc_m0", LAY.m_size(0) * C, LAY.m_size(0) * H)
        for m in range(1, MMAX + 1):
            nl = LAY.m_size(m)
            _lin(sd, f"{bp}.so2_conv_2.so2_m_conv.{m - 1}.fc",
                 2 * nl * C, nl * H, bias=False)
        sd[bp + ".ff_norm.affine_weight"] = 1.0 + 0.1 * torch.randn(
            LMAX + 1, C, dtype=torch.float64)
        sd[bp + ".ff.so3_linear_1.weight"] = torch.randn(
            LMAX + 1, H, C, dtype=torch.float64) / np.sqrt(C)
        sd[bp + ".ff.so3_linear_1.bias"] = 0.1 * torch.randn(H, dtype=torch.float64)
        _lin(sd, bp + ".ff.gating_linear", LMAX * H, C)
        sd[bp + ".ff.so3_linear_2.weight"] = torch.randn(
            LMAX + 1, C, H, dtype=torch.float64) / np.sqrt(H)
        sd[bp + ".ff.so3_linear_2.bias"] = 0.1 * torch.randn(C, dtype=torch.float64)
    sd["backbone.norm.affine_weight"] = 1.0 + 0.1 * torch.randn(LMAX + 1, C, dtype=torch.float64)
    _lin(sd, "heads.energy.mlp.0", C, C)
    _lin(sd, "heads.energy.mlp.2", 1, C)
    return sd


# ---------------------------------------------------------------------------
# The oracle: explicit loops, torch float64, fairchem parameterization
# ---------------------------------------------------------------------------


def _z_rot_t(l, ang):
    K = 2 * l + 1
    f = torch.arange(l, -l - 1, -1, dtype=torch.float64)
    M = torch.zeros(ang.shape[0], K, K, dtype=torch.float64)
    for i in range(K):
        M[:, i, K - 1 - i] = torch.sin(f[i] * ang)
    for i in range(K):
        M[:, i, i] = torch.cos(f[i] * ang)
    return M


def _wigner_t(rhat):
    """Per-l lab-from-edge Wigner blocks, e3nn Jd pipeline, gamma = 0."""
    alpha = torch.atan2(rhat[:, 0], rhat[:, 2])
    beta = torch.acos(torch.clamp(rhat[:, 1], -1.0, 1.0))
    out = []
    for l in range(LMAX + 1):
        J = torch.as_tensor(jd_np(l), dtype=torch.float64)
        out.append(_z_rot_t(l, alpha) @ J @ _z_rot_t(l, beta) @ J)
    return out


def _rms_norm_sh_t(w, x):
    S = (LMAX + 1) ** 2
    bal = torch.zeros(S, dtype=torch.float64)
    o = 0
    for l in range(LMAX + 1):
        bal[o:o + 2 * l + 1] = 1.0 / ((2 * l + 1) * (LMAX + 1))
        o += 2 * l + 1
    ms = (x.pow(2) * bal[None, :, None]).sum(dim=1).mean(dim=1)
    x = x * torch.rsqrt(ms + 1e-12)[:, None, None]
    w_full = torch.repeat_interleave(
        w, torch.tensor([2 * l + 1 for l in range(LMAX + 1)]), dim=0)
    return x * w_full[None]


def _rad_t(sd, prefix, x):
    x = x @ sd[prefix + ".net.0.weight"].T + sd[prefix + ".net.0.bias"]
    mu, var = x.mean(-1, keepdim=True), x.var(-1, keepdim=True, unbiased=False)
    x = (x - mu) / torch.sqrt(var + 1e-5)
    x = x * sd[prefix + ".net.1.weight"] + sd[prefix + ".net.1.bias"]
    x = torch.nn.functional.silu(x)
    return x @ sd[prefix + ".net.3.weight"].T + sd[prefix + ".net.3.bias"]


def _rot_in_t(h_lab, D):
    """(E, S_full, c) -> (E, S_nar, c): per-l transpose + center-row keep."""
    parts = []
    for l in range(LMAX + 1):
        rows = LAY.block_rows(l)
        Dl = D[l][:, :, rows]
        parts.append(torch.einsum("epn,epc->enc", Dl, h_lab[:, l * l:l * l + 2 * l + 1]))
    return torch.cat(parts, dim=1)


def _rot_out_t(y, D):
    parts = []
    for l in range(LMAX + 1):
        rows = LAY.block_rows(l)
        Dl = D[l][:, :, rows]
        parts.append(torch.einsum("epn,enc->epc", Dl, y[:, LAY.block_slices[l]]))
    return torch.cat(parts, dim=1)


def _mmajor_inv_perm():
    """l-major position of each m-major row: scattering m-major results
    back to the l-major stack is a pure gather by the inverse permutation
    (keeps the oracle free of in-place writes for autograd)."""
    order = list(LAY.plus_idx[0])
    for m in range(1, MMAX + 1):
        order += list(LAY.plus_idx[m]) + list(LAY.minus_idx[m])
    inv = np.empty(LAY.size, dtype=np.int64)
    inv[np.array(order)] = np.arange(LAY.size)
    return torch.as_tensor(inv)


def _so2_t(sd, prefix, fr, rad, c_in, c_out, extra_m0):
    E = fr.shape[0]
    parts = []   # m-major order: m0, then (+m, -m) per m
    extra = None
    off = 0
    for m in range(MMAX + 1):
        nl = LAY.m_size(m)
        if m == 0:
            f0 = fr[:, torch.as_tensor(LAY.plus_idx[0])].reshape(E, nl * c_in)
            if rad is not None:
                f0 = f0 * rad[:, off:off + nl * c_in]
            out0 = f0 @ sd[prefix + ".fc_m0.weight"].T + sd[prefix + ".fc_m0.bias"]
            main = out0[:, :nl * c_out]
            if extra_m0:
                extra = out0[:, nl * c_out:]
            parts.append(main.reshape(E, nl, c_out))
        else:
            fp = fr[:, torch.as_tensor(LAY.plus_idx[m])].reshape(E, nl * c_in)
            fm = fr[:, torch.as_tensor(LAY.minus_idx[m])].reshape(E, nl * c_in)
            if rad is not None:
                s = rad[:, off:off + nl * c_in]
                fp, fm = fp * s, fm * s
            W = sd[f"{prefix}.so2_m_conv.{m - 1}.fc.weight"]
            Wr, Wi = W[:nl * c_out], W[nl * c_out:]
            yp = fp @ Wr.T - fm @ Wi.T
            ym = fm @ Wr.T + fp @ Wi.T
            parts.append(yp.reshape(E, nl, c_out))
            parts.append(ym.reshape(E, nl, c_out))
        off += nl * c_in
    y = torch.cat(parts, dim=1)[:, _mmajor_inv_perm()]
    return (y, extra) if extra_m0 else y


def _gate_t(x, gates, full_layout):
    E = x.shape[0]
    g = torch.sigmoid(gates.reshape(E, LMAX, -1))
    counts = [(2 * l + 1) if full_layout else (2 * min(l, MMAX) + 1)
              for l in range(1, LMAX + 1)]
    g_exp = torch.repeat_interleave(g, torch.tensor(counts), dim=1)
    return torch.cat([torch.nn.functional.silu(x[:, :1]),
                      x[:, 1:] * g_exp], dim=1)


def _envelope_t(d):
    # ops/radial.polynomial_cutoff p=6 mirror
    u = torch.clamp(d / CUT, max=1.0)
    p = 6
    val = (1.0 - (p + 1) * (p + 2) / 2 * u**p + p * (p + 2) * u**(p + 1)
           - p * (p + 1) / 2 * u**(p + 2))
    return torch.where(d < CUT, val, torch.zeros_like(val))


def oracle_forward(sd, pos, species, src, dst, charge, spin, dataset):
    """Explicit eSCNMD forward; returns total energy (torch scalar)."""
    S = (LMAX + 1) ** 2
    vec = pos[src] - pos[dst]      # fairchem convention (compute.py:169-173)
    d = vec.norm(dim=1)
    rhat = vec / d[:, None]
    D = _wigner_t(rhat)
    env = _envelope_t(d)
    centers = torch.linspace(0.0, CUT, DB, dtype=torch.float64)
    # fairchem GaussianSmearing: sigma = basis_width_scalar (2.0 in the
    # eSCN/equiformer_v2/UMA lineage) x center spacing — the scalar is a
    # module attr, not a checkpoint tensor (ADVICE r4 medium)
    width = 2.0 * CUT / (DB - 1)  # hardcoded independently of ESCNMDConfig
    gauss = torch.exp(-0.5 * ((d[:, None] - centers) / width) ** 2)

    zemb = sd["backbone.sphere_embedding.weight"][species]
    csd_cat = torch.cat([
        sd["backbone.csd_embedding.charge_embedding.weight"][charge],
        sd["backbone.csd_embedding.spin_embedding.weight"][spin],
        sd["backbone.csd_embedding.dataset_embedding.weight"][dataset],
    ])
    csd = csd_cat @ sd["backbone.csd_embedding.mix_csd.weight"].T + \
        sd["backbone.csd_embedding.mix_csd.bias"]

    N = pos.shape[0]
    h = torch.cat([(zemb + csd[None])[:, None, :],
                   torch.zeros(N, S - 1, C, dtype=torch.float64)], dim=1)

    x_edge = torch.cat([gauss,
                        sd["backbone.source_embedding.weight"][species[src]],
                        sd["backbone.target_embedding.weight"][species[dst]]],
                       dim=1)

    # edge-degree embedding
    w = _rad_t(sd, "backbone.edge_degree_embedding.rad_func", x_edge)
    w = w.reshape(-1, LMAX + 1, C)
    zeros_rest = torch.zeros(len(d), LAY.size - (LMAX + 1), C,
                             dtype=torch.float64)
    y = torch.cat([w, zeros_rest], dim=1)[:, _mmajor_inv_perm()]
    msg = _rot_out_t(y, D) * env[:, None, None]
    agg = torch.zeros(N, S, C, dtype=torch.float64)
    agg.index_add_(0, dst, msg)
    h = h + agg / AVG

    for i in range(NL):
        bp = f"backbone.blocks.{i}"
        hn = _rms_norm_sh_t(sd[bp + ".norm_1.affine_weight"], h)
        hn = torch.cat([hn[:, :1] + csd[None, None], hn[:, 1:]], dim=1)
        rad = _rad_t(sd, bp + ".so2_conv_1.rad_func", x_edge)
        fr = torch.cat([_rot_in_t(hn[src], D), _rot_in_t(hn[dst], D)], dim=2)
        y1, gates = _so2_t(sd, bp + ".so2_conv_1", fr, rad, 2 * C, H,
                           extra_m0=True)
        y1 = _gate_t(y1, gates, full_layout=False)
        y2 = _so2_t(sd, bp + ".so2_conv_2", y1, None, H, C, extra_m0=False)
        msg = _rot_out_t(y2, D) * env[:, None, None]
        agg = torch.zeros(N, S, C, dtype=torch.float64)
        agg.index_add_(0, dst, msg)
        h = h + agg / AVG
        # FFN
        xf = _rms_norm_sh_t(sd[bp + ".ff_norm.affine_weight"], h)
        gates = xf[:, 0] @ sd[bp + ".ff.gating_linear.weight"].T + \
            sd[bp + ".ff.gating_linear.bias"]
        w1 = torch.repeat_interleave(
            sd[bp + ".ff.so3_linear_1.weight"],
            torch.tensor([2 * l + 1 for l in range(LMAX + 1)]), dim=0)
        hf = torch.einsum("nsc,shc->nsh", xf, w1)
        hf = torch.cat([hf[:, :1] + sd[bp + ".ff.so3_linear_1.bias"],
                        hf[:, 1:]], dim=1)
        hf = _gate_t(hf, gates, full_layout=True)
        w2 = torch.repeat_interleave(
            sd[bp + ".ff.so3_linear_2.weight"],
            torch.tensor([2 * l + 1 for l in range(LMAX + 1)]), dim=0)
        yf = torch.einsum("nsh,sch->nsc", hf, w2)
        yf = torch.cat([yf[:, :1] + sd[bp + ".ff.so3_linear_2.bias"],
                        yf[:, 1:]], dim=1)
        h = h + yf

    h = _rms_norm_sh_t(sd["backbone.norm.affine_weight"], h)
    s = h[:, 0]
    e = torch.nn.functional.silu(
        s @ sd["heads.energy.mlp.0.weight"].T + sd["heads.energy.mlp.0.bias"])
    e = e @ sd["heads.energy.mlp.2.weight"].T + sd["heads.energy.mlp.2.bias"]
    return e.sum()


def _cluster(rng, n=36, box=30.0, spread=5.5):
    """Aperiodic cluster centered in a huge box: no wrap, no offsets —
    the oracle's brute-force edge list matches the pipeline's exactly."""
    cart = rng.normal(0.0, spread, (n, 3))
    # enforce a minimum separation so the cluster is physical
    for _ in range(40):
        diff = cart[:, None] - cart[None, :]
        dist = np.linalg.norm(diff, axis=-1) + np.eye(n) * 1e9
        close = dist < 1.2
        if not close.any():
            break
        push = np.where(close[..., None], diff * 0.2, 0.0).sum(axis=1)
        cart = cart + push
    cart = cart + box / 2
    lattice = np.eye(3) * box
    species = rng.integers(0, Z, n).astype(np.int32)
    return cart, lattice, species


@pytest.fixture(scope="module")
def converted():
    sd = synthetic_escn_state_dict()
    model = ESCNMD(CFG)
    params = model.init(jax.random.PRNGKey(0))
    params, report = from_torch("escn", sd, params, model=model)
    return sd, model, params, report


def test_zero_unmapped(converted):
    _, _, _, report = converted
    assert report["unused_torch"] == []


def test_energy_force_parity_vs_torch_oracle(converted):
    sd, model, _, _ = converted
    jax.config.update("jax_enable_x64", True)
    try:
        # init + convert UNDER x64: set_in casts checkpoint values to the
        # leaf dtype, so float32-initialized leaves would round the weights
        # and cap parity at ~1e-7
        params = model.init(jax.random.PRNGKey(0))
        params, _ = from_torch("escn", sd, params, model=model)
        rng = np.random.default_rng(5)
        cart, lattice, species = _cluster(rng)
        charge, spin, dataset = 2, 1, 1

        # oracle: brute-force directed edge list within the cutoff
        n = len(cart)
        diff = cart[:, None] - cart[None, :]
        dist = np.linalg.norm(diff, axis=-1)
        src, dst = np.nonzero((dist < CUT) & (dist > 0))
        pos_t = torch.tensor(cart, dtype=torch.float64, requires_grad=True)
        e_t = oracle_forward(sd, pos_t, torch.as_tensor(species, dtype=torch.long),
                             torch.as_tensor(src), torch.as_tensor(dst),
                             charge - CFG.charge_min, spin, dataset)
        e_t.backward()
        f_ref = -pos_t.grad.numpy()

        from distmlip_tpu.neighbors import neighbor_list_numpy
        from distmlip_tpu.parallel import make_potential_fn
        from distmlip_tpu.partition import build_partitioned_graph, build_plan

        nl = neighbor_list_numpy(cart, lattice, [1, 1, 1], CUT)
        plan = build_plan(nl, lattice, [1, 1, 1], 1, CUT, 0.0, False)
        graph, host = build_partitioned_graph(
            plan, nl, species, lattice, dtype=np.float64,
            system={"charge": charge, "spin": spin, "dataset": dataset})
        pot = make_potential_fn(model.energy_fn, None, compute_stress=False)
        out = pot(params, graph, graph.positions)
        e_j = float(out["energy"])
        f_j = host.gather_owned(np.asarray(out["forces"]), n)

        assert abs(e_j - float(e_t)) / n < 1e-9, (e_j, float(e_t))
        np.testing.assert_allclose(f_j, f_ref, atol=1e-8)
    finally:
        jax.config.update("jax_enable_x64", False)


def test_mole_shaped_dict_converts():
    """Expert-stacked (K, out, in) SO(2) weights convert into a
    num_experts=3 model with zero unmapped backbone tensors."""
    K = 3
    sd = synthetic_escn_state_dict()
    for k in list(sd):
        if ".so2_conv_" in k and (".fc_m0.weight" in k or ".fc.weight" in k):
            sd[k] = torch.randn((K,) + tuple(sd[k].shape),
                                dtype=torch.float64) / np.sqrt(sd[k].shape[-1])
    cfg = ESCNMDConfig(**{**CFG.__dict__, "num_experts": K})
    model = ESCNMD(cfg)
    params = model.init(jax.random.PRNGKey(1))
    params, report = from_torch("escn", sd, params, model=model, strict=False)
    # every backbone tensor maps; only the (framework-side) MOLE gate has
    # no fairchem analogue in the synthetic dict
    assert report["unused_torch"] == []


def test_mole_routing_tensors_refused_even_nonstrict():
    """A dict carrying MOLE expert-ROUTING tensors must be refused loudly —
    even under strict=False — because this framework's gate routes on
    composition+csd and cannot host upstream routing weights; converting
    around them would leave silently-random expert mixtures (ADVICE r4)."""
    sd = synthetic_escn_state_dict()
    sd["backbone.mole_coefficient_net.0.weight"] = torch.randn(
        4, 8, dtype=torch.float64)
    model = ESCNMD(CFG)
    params = model.init(jax.random.PRNGKey(2))
    with pytest.raises(ValueError, match="routing"):
        from_torch("escn", sd, params, model=model, strict=False)


def test_mole_guard_word_boundary_no_false_positive():
    """Keys merely CONTAINING 'mole' as a substring (molecule_embedding)
    must not trip the routing refusal — they fall through to the normal
    unused-tensor report."""
    sd = synthetic_escn_state_dict()
    sd["backbone.molecule_embedding.weight"] = torch.randn(
        4, 8, dtype=torch.float64)
    model = ESCNMD(CFG)
    params = model.init(jax.random.PRNGKey(2))
    _, report = from_torch("escn", sd, params, model=model, strict=False)
    assert "backbone.molecule_embedding.weight" in report["unused_torch"]


def test_export_roundtrip_converts(tmp_path):
    """tools/export_upstream escn: a fairchem-style checkpoint file
    ({"state_dict": {"module....": tensors}}) exports to npz and converts
    with zero unmapped tensors — the full offline-ingestion pipeline."""
    from distmlip_tpu.tools.export_upstream import main as export_main

    sd = synthetic_escn_state_dict()
    ckpt = str(tmp_path / "uma.pt")
    torch.save({"state_dict": {("module." + k): v for k, v in sd.items()}},
               ckpt)
    out = str(tmp_path / "uma.npz")
    assert export_main(["escn", ckpt, out]) == 0
    back = dict(np.load(out))
    assert set(back) == set(sd)
    model = ESCNMD(CFG)
    params, report = from_torch("escn", back,
                                model.init(jax.random.PRNGKey(3)),
                                model=model)
    assert report["unused_torch"] == []
