"""The one-command upstream-parity verifier must run its full pipeline
(export passthrough -> config inference from shapes -> strict convert ->
P=1/P=2 evaluation) on every family's synthetic checkpoint, exiting 3
(= converted + self-consistent, upstream package not importable here).
Wherever mace-torch / matgl / fairchem ARE installed the same command
performs the numeric upstream comparison — the recipe in PARITY.md
(VERDICT r4 item 6).
"""

import numpy as np
import pytest
import torch

import jax

from distmlip_tpu.tools.verify_upstream import main as vu_main

pytestmark = pytest.mark.slow


def _npz(tmp_path, name, sd):
    path = str(tmp_path / f"{name}.npz")
    np.savez_compressed(
        path, **{k: (v.detach().numpy() if hasattr(v, "detach") else v)
                 for k, v in sd.items()})
    return path


def test_mace_dry_run(tmp_path):
    from distmlip_tpu.models import MACE
    from tests.test_convert import SMALL, synthetic_mace_state_dict

    sd = synthetic_mace_state_dict(MACE(SMALL), np.random.default_rng(0))
    assert vu_main(["mace", _npz(tmp_path, "mace", sd)]) == 3


def test_chgnet_dry_run(tmp_path):
    from tests.test_convert_chgnet import TCHGNet

    torch.manual_seed(0)
    sd = TCHGNet(5, 8, 6, 3, 2, 5.0, 3.0).state_dict()
    assert vu_main(["chgnet", _npz(tmp_path, "chgnet", sd),
                    "--set", "cutoff=5.0", "--set", "bond_cutoff=3.0"]) == 3


def test_tensornet_dry_run(tmp_path):
    from tests.test_convert_tensornet import TTensorNet

    torch.manual_seed(0)
    sd = TTensorNet(5, 8, 6, 2, 5.0).state_dict()
    assert vu_main(["tensornet", _npz(tmp_path, "tensornet", sd),
                    "--set", "cutoff=5.0"]) == 3


def test_escn_dry_run(tmp_path):
    from tests.test_convert_escn import synthetic_escn_state_dict

    sd = synthetic_escn_state_dict()
    assert vu_main(["escn", _npz(tmp_path, "escn", sd),
                    "--set", "avg_degree=9.0"]) == 3


def test_mace_inference_recovers_config(tmp_path):
    """Shape-based inference must reproduce the generating config exactly
    (l_max via path-count matching, hidden_lmax via contraction count,
    correlation via U_matrix orders)."""
    from distmlip_tpu.models import MACE
    from distmlip_tpu.tools.verify_upstream import infer_mace
    from tests.test_convert import SMALL, synthetic_mace_state_dict

    sd = synthetic_mace_state_dict(MACE(SMALL), np.random.default_rng(0))
    sd = {k: np.asarray(v) for k, v in sd.items()}
    cfg, assumed, zs, _ = infer_mace(sd, {})
    for field in ("num_species", "channels", "l_max", "a_lmax",
                  "hidden_lmax", "correlation", "num_interactions",
                  "num_bessel", "radial_mlp", "cutoff", "cutoff_p", "zbl"):
        assert getattr(cfg, field) == getattr(SMALL, field), field
