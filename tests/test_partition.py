"""Partitioner invariants: disjoint cover, edge conservation, halo alignment,
line-graph equivalence vs a brute-force global line graph."""

import numpy as np
import pytest

from distmlip_tpu.neighbors import neighbor_list_numpy
from distmlip_tpu.partition import PartitionError, build_plan
from tests.conftest import random_cell

R = 3.0
BOND_R = 2.0


def make_plan(rng, P, n_atoms=None, box=None, bond=False):
    # slab width must exceed 2*R for the one-destination halo invariant
    box = box or max(16.0, P * 8.0)
    n_atoms = n_atoms or int(0.02 * box**3)
    cart, lattice, species, pbc = random_cell(rng, n_atoms=n_atoms, box=box)
    nl = neighbor_list_numpy(cart, lattice, pbc, R, bond_r=BOND_R)
    plan = build_plan(nl, lattice, pbc, P, R, BOND_R, use_bond_graph=bond)
    return plan, nl, lattice


@pytest.mark.parametrize("P", [1, 2, 4])
def test_owned_disjoint_cover(rng, P):
    plan, nl, _ = make_plan(rng, P)
    n = nl.wrapped_cart.shape[0]
    seen = np.zeros(n, dtype=int)
    for p in range(P):
        owned = plan.global_ids[p][: plan.owned_counts[p]]
        seen[owned] += 1
    np.testing.assert_array_equal(seen, np.ones(n, dtype=int))


@pytest.mark.parametrize("P", [1, 2, 4])
def test_edge_conservation(rng, P):
    plan, nl, _ = make_plan(rng, P)
    all_ids = np.concatenate([plan.edge_ids[p] for p in range(P)])
    assert len(all_ids) == nl.num_edges
    np.testing.assert_array_equal(np.sort(all_ids), np.arange(nl.num_edges))


@pytest.mark.parametrize("P", [2, 4])
def test_edge_localization(rng, P):
    """Local endpoints must map back to the correct global endpoints."""
    plan, nl, _ = make_plan(rng, P)
    for p in range(P):
        g = plan.global_ids[p]
        np.testing.assert_array_equal(g[plan.src_local[p]], nl.src[plan.edge_ids[p]])
        np.testing.assert_array_equal(g[plan.dst_local[p]], nl.dst[plan.edge_ids[p]])


@pytest.mark.parametrize("P", [2, 4])
def test_halo_alignment(rng, P):
    """to_q section of p and from_p section of q hold the same global ids in
    the same order — the exchange is then a pure slot copy."""
    plan, _, _ = make_plan(rng, P)
    for p in range(P):
        for q in range(P):
            if p == q:
                continue
            ts, te = plan.section(p, "to", q)
            fs, fe = plan.section(q, "from", p)
            np.testing.assert_array_equal(
                plan.global_ids[p][ts:te], plan.global_ids[q][fs:fe]
            )


@pytest.mark.parametrize("P", [2, 4])
def test_border_reach(rng, P):
    """Every cross-partition edge's src is present in the dst's partition."""
    plan, nl, _ = make_plan(rng, P)
    for p in range(P):
        assert np.all(plan.g2l[p][nl.src[plan.edge_ids[p]]] >= 0)


def test_too_many_partitions_raises(rng):
    cart, lattice, _, pbc = random_cell(rng, n_atoms=60, box=10.0)
    nl = neighbor_list_numpy(cart, lattice, pbc, R)
    with pytest.raises(PartitionError):
        build_plan(nl, lattice, pbc, 8, R)


def _global_line_graph(nl):
    """Brute-force directed line graph over within-bond edges.

    (e1=(s->d), e2=(d->k)) with k != s; returns the set of global edge-id
    pairs plus the center atom d.
    """
    W = np.nonzero(nl.bond_mask)[0]
    pairs = set()
    by_src = {}
    for e in W:
        by_src.setdefault(int(nl.src[e]), []).append(e)
    for e1 in W:
        d = int(nl.dst[e1])
        for e2 in by_src.get(d, []):
            if int(nl.dst[e2]) == int(nl.src[e1]):
                continue
            pairs.add((int(e1), int(e2), d))
    return pairs


@pytest.mark.parametrize("P", [1, 2, 4])
def test_line_graph_equivalence(rng, P):
    plan, nl, _ = make_plan(rng, P, bond=True)
    got = set()
    for p in range(P):
        b_edge = plan.bond_global_edge[p]
        g = plan.global_ids[p]
        for ls, ld, c in zip(plan.line_src[p], plan.line_dst[p], plan.line_center_local[p]):
            got.add((int(b_edge[ls]), int(b_edge[ld]), int(g[c])))
    want = _global_line_graph(nl)
    assert got == want


@pytest.mark.parametrize("P", [2, 4])
def test_line_graph_no_duplicates(rng, P):
    plan, _, _ = make_plan(rng, P, bond=True)
    total, uniq = 0, set()
    for p in range(P):
        b_edge = plan.bond_global_edge[p]
        for ls, ld in zip(plan.line_src[p], plan.line_dst[p]):
            uniq.add((int(b_edge[ls]), int(b_edge[ld])))
            total += 1
    assert total == len(uniq)


@pytest.mark.parametrize("P", [2, 4])
def test_bond_halo_alignment(rng, P):
    plan, _, _ = make_plan(rng, P, bond=True)
    for p in range(P):
        for q in range(P):
            if p == q:
                continue
            ts, te = plan.bond_section(p, "to", q)
            fs, fe = plan.bond_section(q, "from", p)
            np.testing.assert_array_equal(
                plan.bond_global_edge[p][ts:te], plan.bond_global_edge[q][fs:fe]
            )


@pytest.mark.parametrize("P", [1, 2, 4])
def test_bond_mapping(rng, P):
    """Owned bond nodes map to local edges carrying the same global edge."""
    plan, nl, _ = make_plan(rng, P, bond=True)
    for p in range(P):
        local_edge_global = plan.edge_ids[p][plan.bond_mapping_edge[p]]
        bond_global = plan.bond_global_edge[p][plan.bond_mapping_bond[p]]
        np.testing.assert_array_equal(local_edge_global, bond_global)


@pytest.mark.parametrize("P", [2, 4])
@pytest.mark.parametrize("bond", [False, True])
def test_native_matches_numpy_oracle(rng, P, bond):
    """The C++ partitioner must reproduce the numpy plan EXACTLY."""
    from distmlip_tpu.neighbors.native import native_available
    from distmlip_tpu.neighbors import neighbor_list_numpy

    if not native_available():
        pytest.skip("native lib unavailable")
    box = max(16.0, P * 8.0)
    cart, lattice, _, pbc = random_cell(rng, n_atoms=int(0.02 * box**3), box=box)
    nl = neighbor_list_numpy(cart, lattice, pbc, R, bond_r=BOND_R)
    p_np = build_plan(nl, lattice, pbc, P, R, BOND_R, bond, impl="numpy")
    p_nat = build_plan(nl, lattice, pbc, P, R, BOND_R, bond, impl="native")
    for p in range(P):
        np.testing.assert_array_equal(p_np.global_ids[p], p_nat.global_ids[p])
        np.testing.assert_array_equal(p_np.node_markers[p], p_nat.node_markers[p])
        np.testing.assert_array_equal(p_np.edge_ids[p], p_nat.edge_ids[p])
        np.testing.assert_array_equal(p_np.src_local[p], p_nat.src_local[p])
        np.testing.assert_array_equal(p_np.dst_local[p], p_nat.dst_local[p])
        if bond:
            np.testing.assert_array_equal(p_np.bond_markers[p], p_nat.bond_markers[p])
            np.testing.assert_array_equal(
                p_np.bond_global_edge[p], p_nat.bond_global_edge[p])
            np.testing.assert_array_equal(p_np.line_src[p], p_nat.line_src[p])
            np.testing.assert_array_equal(p_np.line_dst[p], p_nat.line_dst[p])
            np.testing.assert_array_equal(
                p_np.line_center_local[p], p_nat.line_center_local[p])
            np.testing.assert_array_equal(
                p_np.bond_mapping_edge[p], p_nat.bond_mapping_edge[p])
            np.testing.assert_array_equal(
                p_np.bond_mapping_bond[p], p_nat.bond_mapping_bond[p])
    np.testing.assert_array_equal(p_np.nodes_to_partition, p_nat.nodes_to_partition)


def test_native_partitioner_rejects_multidest(rng):
    from distmlip_tpu.neighbors.native import native_available
    from distmlip_tpu.neighbors import neighbor_list_numpy

    if not native_available():
        pytest.skip("native lib unavailable")
    cart, lattice, _, pbc = random_cell(rng, n_atoms=200, box=16.0)
    nl = neighbor_list_numpy(cart, lattice, pbc, R)
    # P=4 on a 16 A box: slab 4 A > R so check_partition_size passes, but
    # nodes reach both sides (width < 2R) -> both impls must raise
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(PartitionError):
            build_plan(nl, lattice, pbc, 4, R, impl="native")
        with pytest.raises(PartitionError):
            build_plan(nl, lattice, pbc, 4, R, impl="numpy")


def test_make_walls_atoms_on_planes():
    """Perfect supercells put whole atom planes exactly at k/P: walls must
    nudge off them in either direction, stay strictly increasing, and stay
    inside (0, 1)."""
    from distmlip_tpu.partition.partitioner import EPSILON, make_walls

    P = 4
    frac = np.repeat(np.arange(P) / P, 16)          # planes at 0, .25, .5, .75
    walls = make_walls(frac, P)
    assert np.all(np.diff(walls) > 0)
    assert walls[0] > 0.0 and walls[-1] < 1.0
    assert np.abs(frac[:, None] - walls[None, :]).min() >= EPSILON
    # planes crowding a wall from above force a DOWNWARD nudge
    dense_above = np.concatenate(
        [frac, 0.25 + np.arange(1, 30) * 10 * EPSILON]
    )
    walls2 = make_walls(dense_above, P)
    assert walls2[0] < 0.25
    assert np.abs(dense_above[:, None] - walls2[None, :]).min() >= EPSILON
    assert np.all(np.diff(walls2) > 0)


def test_perfect_crystal_partition_end_to_end(rng):
    """A perfect (unperturbed) supercell — atoms exactly on wall planes —
    must partition with all invariants intact."""
    from distmlip_tpu import geometry

    unit = np.array([[0.0, 0.0, 0.0], [0.5, 0.5, 0.5]])
    frac, lattice = geometry.make_supercell(unit, np.eye(3) * 4.0, (8, 2, 2))
    cart = geometry.frac_to_cart(frac, lattice)
    nl = neighbor_list_numpy(cart, lattice, [1, 1, 1], R, bond_r=0.0)
    plan = build_plan(nl, lattice, [1, 1, 1], 4, R)
    n = len(cart)
    seen = np.zeros(n, dtype=int)
    for p in range(4):
        mk = plan.node_markers[p]
        owned = plan.global_ids[p][: mk[1 + 4]]
        seen[owned] += 1
    assert np.all(seen == 1)
    assert sum(len(e) for e in plan.edge_ids) == nl.num_edges
