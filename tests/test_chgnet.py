"""CHGNet model physics + distributed equivalence (bond graph + angles)."""

import jax
import numpy as np
import pytest

from distmlip_tpu.models.chgnet import CHGNet, CHGNetConfig
from tests.utils import make_crystal, run_potential

CFG = CHGNetConfig(
    num_species=4, units=16, num_rbf=6, num_angle=4, num_blocks=3,
    cutoff=3.2, bond_cutoff=2.6,
)
A_LAT = 3.5  # fcc nn distance a/sqrt(2) = 2.47 A < bond_cutoff
MODEL = CHGNet(CFG)


@pytest.fixture(scope="module")
def params():
    return MODEL.init(jax.random.PRNGKey(0))


def _run(params, cart, lattice, species, nparts, **kw):
    return run_potential(
        MODEL.energy_fn, params, cart, lattice, species, CFG.cutoff, nparts,
        bond_r=CFG.bond_cutoff, use_bond_graph=True, **kw,
    )


def test_distributed_matches_single_device(rng, params):
    cart, lattice, species = make_crystal(rng, reps=(8, 4, 4), a=A_LAT)
    e1, f1, s1 = _run(params, cart, lattice, species, 1)
    e4, f4, s4 = _run(params, cart, lattice, species, 4)
    # non-degeneracy guard: a position-independent model gives forces at
    # fp32 grad-noise level (<= ~1e-7). The floor sits well above that but
    # far below any real random-init magnitude — the init's scale varies
    # a few x across jax builds (observed 7e-3 here vs 1e-2 historically),
    # which must not fail the guard.
    assert np.abs(f1).max() > 1e-5
    assert abs(e1 - e4) < 1e-4 * max(1.0, abs(e1))
    np.testing.assert_allclose(f1, f4, atol=2e-4)
    np.testing.assert_allclose(s1, s4, atol=1e-5)


def test_angles_affect_energy(rng, params):
    """The bond-graph path must contribute: disabling it changes the energy."""
    cart, lattice, species = make_crystal(rng, reps=(3, 3, 3), a=A_LAT)
    e_bg, _, _ = _run(params, cart, lattice, species, 1)
    e_nobg, _, _ = run_potential(
        MODEL.energy_fn, params, cart, lattice, species, CFG.cutoff, 1,
        bond_r=CFG.bond_cutoff, use_bond_graph=False,
    )
    assert abs(e_bg - e_nobg) > 1e-3


def test_rotation_invariance(rng, params):
    cart, lattice, species = make_crystal(rng, reps=(3, 3, 3), a=A_LAT)
    q, _ = np.linalg.qr(rng.normal(size=(3, 3)))
    if np.linalg.det(q) < 0:
        q[:, 0] *= -1
    e1, f1, _ = _run(params, cart, lattice, species, 1)
    e2, f2, _ = _run(params, cart @ q, lattice @ q, species, 1)
    assert abs(e1 - e2) < 5e-4 * max(1.0, abs(e1))
    np.testing.assert_allclose(f1 @ q, f2, atol=3e-4)


def test_forces_match_finite_difference(rng, params):
    jax.config.update("jax_enable_x64", True)
    try:
        cart, lattice, species = make_crystal(rng, reps=(2, 2, 2), a=A_LAT, noise=0.08)
        cart = cart.astype(np.float64)

        def energy(c):
            e, f, _ = run_potential(
                MODEL.energy_fn,
                jax.tree.map(lambda x: jax.numpy.asarray(x, jax.numpy.float64), params),
                c, lattice, species, CFG.cutoff, 1,
                bond_r=CFG.bond_cutoff, use_bond_graph=True,
                compute_stress=False, dtype=np.float64,
            )
            return e, f

        _, forces = energy(cart)
        # degeneracy floor, not an init-magnitude check (see
        # test_distributed_matches_single_device)
        assert np.abs(forces).max() > 1e-5
        h = 1e-5
        for atom, ax in [(0, 0), (7, 1), (13, 2)]:
            cp, cm = cart.copy(), cart.copy()
            cp[atom, ax] += h
            cm[atom, ax] -= h
            ep, _ = energy(cp)
            em, _ = energy(cm)
            f_fd = -(ep - em) / (2 * h)
            np.testing.assert_allclose(forces[atom, ax], f_fd, rtol=1e-5, atol=1e-7)
    finally:
        jax.config.update("jax_enable_x64", False)


def test_energy_smooth_at_cutoff(rng, params):
    lattice = np.eye(3) * 20.0
    species = np.zeros(3, np.int32)
    es = []
    for d in np.linspace(CFG.cutoff - 0.02, CFG.cutoff + 0.02, 9):
        cart = np.array([[5.0, 5.0, 5.0], [5.0 + d, 5.0, 5.0], [5.0, 6.5, 5.0]])
        # third atom within bond range of atom 0 -> line graph non-empty
        e, _, _ = _run(params, cart, lattice, species, 1, compute_stress=False)
        es.append(e)
    assert np.ptp(es) < 2e-3


def test_skin_shell_edges_contribute_nothing(rng, params):
    """A neighbor list built at cutoff+skin (MD reuse) must give the same
    energy/forces as one built at the exact cutoffs: skin-shell edges and
    bonds are masked out of every message path (matgl's graph simply does
    not contain them)."""
    cart, lattice, species = make_crystal(rng, reps=(3, 3, 3), a=A_LAT)
    e0, f0, _ = _run(params, cart, lattice, species, 1, compute_stress=False)
    from distmlip_tpu.neighbors import neighbor_list_numpy
    from distmlip_tpu.parallel import make_potential_fn
    from distmlip_tpu.partition import build_plan, build_partitioned_graph

    skin = 0.4
    nl = neighbor_list_numpy(cart, lattice, [1, 1, 1], CFG.cutoff + skin,
                             bond_r=CFG.bond_cutoff + skin)
    plan = build_plan(nl, lattice, [1, 1, 1], 1, CFG.cutoff + skin,
                      CFG.bond_cutoff + skin, True)
    graph, host = build_partitioned_graph(plan, nl, species, lattice)
    assert int(np.asarray(graph.edge_mask).sum()) > 0
    pot = make_potential_fn(MODEL.energy_fn, None, compute_stress=False)
    out = pot(params, graph, graph.positions)
    e1 = float(out["energy"])
    f1 = host.gather_owned(np.asarray(out["forces"]), len(cart))
    assert abs(e0 - e1) < 1e-4 * max(1.0, abs(e0))
    np.testing.assert_allclose(f0, f1, atol=2e-4)


def test_magmom_readout(rng, params):
    cart, lattice, species = make_crystal(rng, reps=(2, 2, 2), a=A_LAT)
    from distmlip_tpu.neighbors import neighbor_list_numpy
    from distmlip_tpu.parallel.halo import local_graph_from_stacked
    from distmlip_tpu.partition import build_plan, build_partitioned_graph

    nl = neighbor_list_numpy(cart, lattice, [1, 1, 1], CFG.cutoff)
    plan = build_plan(nl, lattice, [1, 1, 1], 1, CFG.cutoff)
    graph, host = build_partitioned_graph(plan, nl, species, lattice)
    lg, pos = local_graph_from_stacked(graph, None)
    m = MODEL.magmom_fn(params, lg, pos)
    assert m.shape == (graph.n_cap,)
    assert np.all(np.asarray(m)[: len(cart)] >= 0)


def test_magmoms_through_calculator(rng, params):
    """compute_magmom surfaces the sitewise readout through
    DistPotential.calculate (reference PESCalculator_Dist magmoms,
    implementations/matgl/ase.py:53-127), identical across partitionings."""
    from distmlip_tpu.calculators import Atoms, DistPotential

    cart, lattice, species = make_crystal(rng, reps=(4, 2, 2), a=A_LAT)
    atoms = Atoms(numbers=species + 1, positions=cart, cell=lattice)
    smap = np.concatenate([[0], np.arange(0, 8)]).astype(np.int32)
    outs = {}
    for P in (1, 2):
        pot = DistPotential(MODEL, params, num_partitions=P,
                            species_map=smap, compute_magmom=True)
        outs[P] = pot.calculate(atoms)
    assert outs[1]["magmoms"].shape == (len(atoms),)
    np.testing.assert_allclose(outs[1]["magmoms"], outs[2]["magmoms"],
                               atol=1e-5)


@pytest.mark.slow
def test_ensemble_magmoms(rng, params):
    """compute_magmom through EnsemblePotential: both the stacked (vmapped
    site fn) and sequential paths surface per-member + mean magmoms."""
    from distmlip_tpu.calculators import Atoms, EnsemblePotential

    cart, lattice, species = make_crystal(rng, reps=(4, 2, 2), a=A_LAT)
    atoms = Atoms(numbers=species + 1, positions=cart, cell=lattice)
    smap = np.concatenate([[0], np.arange(0, 8)]).astype(np.int32)
    p2 = MODEL.init(jax.random.PRNGKey(9))
    outs = {}
    for stacked in (True, False):
        ens = EnsemblePotential(MODEL, [params, p2], stacked=stacked,
                                num_partitions=2, species_map=smap,
                                compute_magmom=True)
        outs[stacked] = ens.calculate(atoms)
        assert outs[stacked]["magmoms"].shape == (len(atoms),)
        assert outs[stacked]["magmoms_all"].shape == (2, len(atoms))
    np.testing.assert_allclose(outs[True]["magmoms"], outs[False]["magmoms"],
                               atol=1e-5)
