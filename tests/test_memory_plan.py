"""Static HBM planner tests: estimator vs the XLA oracle, the
memory_budget pass, and memory-aware autobatching/admission.

Fast subset is tier1-marked; the full 22-program estimator-vs-oracle
sweep (one real XLA compile per program) is slow-marked.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distmlip_tpu.analysis import (Program, Severity, exit_code, get_passes,
                                   run_passes)
from distmlip_tpu.analysis.memory import (MemoryPlan, analyze_memory,
                                          aval_bytes, oracle_peak_bytes)

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))

ORACLE_BAND = (0.5, 2.0)        # the acceptance criterion: within 2x


def _pair_graph(rng, nparts=1, reps=(4, 2, 2)):
    from distmlip_tpu.models.pair import PairConfig, PairPotential
    from distmlip_tpu.neighbors import neighbor_list_numpy
    from distmlip_tpu.partition import build_partitioned_graph, build_plan
    from tests.utils import make_crystal

    model = PairPotential(PairConfig(cutoff=3.2))
    params = model.init(jax.random.PRNGKey(0))
    cart, lattice, species = make_crystal(rng, reps=reps, a=3.5)
    nl = neighbor_list_numpy(cart, lattice, [1, 1, 1], 3.2)
    plan = build_plan(nl, lattice, [1, 1, 1], nparts, 3.2, 0.0, False)
    graph, _ = build_partitioned_graph(plan, nl, species, lattice)
    return model, params, graph


# ---------------------------------------------------------------------------
# estimator mechanics (toy fixtures; no model tracing)
# ---------------------------------------------------------------------------


@pytest.mark.memory
@pytest.mark.tier1
def test_plan_shape_and_composition():
    def f(x, w):
        h = jnp.tanh(x @ w)
        return (h @ w).sum()

    x = jnp.ones((256, 128), jnp.float32)
    w = jnp.ones((128, 128), jnp.float32)
    jaxpr = jax.make_jaxpr(f)(x, w)
    plan = analyze_memory(jaxpr)
    assert isinstance(plan, MemoryPlan)
    # args resident: x (128KiB) + w (64KiB)
    assert plan.arg_bytes == 256 * 128 * 4 + 128 * 128 * 4
    # peak covers at least the args plus one (256,128) temp
    assert plan.peak_bytes >= plan.arg_bytes + 256 * 128 * 4
    assert plan.temp_peak_bytes > 0
    assert plan.n_eqns >= 3
    assert plan.peak_bytes == plan.resident_bytes + plan.temp_peak_bytes


@pytest.mark.memory
@pytest.mark.tier1
def test_donated_input_reuse():
    """A donated input dies at its last use; a held one is resident for
    the whole program — the peaks must differ by about the input size."""
    def f(x):
        y = x * 2.0                 # x's last use: dies here if donated
        z = jnp.tanh(y)
        w = z * 0.5 + 1.0
        return w.sum()

    x = jnp.ones((1024, 256), jnp.float32)
    jaxpr = jax.make_jaxpr(f)(x)
    held = analyze_memory(jaxpr)
    donated = analyze_memory(jaxpr, donated=[0])
    nbytes = 1024 * 256 * 4
    assert held.peak_bytes >= donated.peak_bytes
    # downstream of x's death two same-size temps are transiently live;
    # holding x on top of them costs about one extra buffer
    assert held.peak_bytes - donated.peak_bytes >= nbytes // 2
    # bool-mask spellings (list AND numpy array) are equivalent
    assert analyze_memory(jaxpr, donated=[True]).peak_bytes \
        == donated.peak_bytes
    assert analyze_memory(jaxpr, donated=np.array([True])).peak_bytes \
        == donated.peak_bytes


@pytest.mark.memory
@pytest.mark.tier1
def test_scan_carry_and_ys_residency():
    """A scan charges its stacked ys at the call site and a double-buffered
    carry; the loop body's operands stay held for the whole call."""
    carry_shape = (512, 64)                      # 128 KiB f32
    T = 8

    def step(c, _):
        c = jnp.tanh(c) * 0.5
        return c, c

    def f(c0):
        c, ys = jax.lax.scan(step, c0, jnp.arange(T, dtype=jnp.float32))
        return c.sum() + ys.sum()

    c0 = jnp.ones(carry_shape, jnp.float32)
    jaxpr = jax.make_jaxpr(f)(c0)
    plan = analyze_memory(jaxpr)
    carry_b = int(np.prod(carry_shape)) * 4
    # resident: c0 (arg) + stacked ys (T x carry) + 2x carry double-buffer
    assert plan.peak_bytes >= carry_b + T * carry_b + 2 * carry_b
    # and the scan shows up as a transient window
    assert any(t.primitive == "scan" for t in plan.transients)


@pytest.mark.memory
@pytest.mark.tier1
def test_shard_map_args_scale_per_device():
    """Program args sharded into a shard_map are charged per-device."""
    from jax.sharding import PartitionSpec as P

    from distmlip_tpu.parallel import SPATIAL_AXIS, graph_mesh
    from distmlip_tpu.parallel.runtime import _NO_CHECK, shard_map

    mesh = graph_mesh(4)
    x = jnp.ones((4, 1024, 64), jnp.float32)     # 1 MiB global

    def local(xs):
        return jax.lax.psum((xs * 2.0).sum(), SPATIAL_AXIS)

    fn = shard_map(local, mesh=mesh, in_specs=(P(SPATIAL_AXIS),),
                   out_specs=P(), **_NO_CHECK)
    jaxpr = jax.make_jaxpr(fn)(x)
    plan = analyze_memory(jaxpr)
    nbytes = 4 * 1024 * 64 * 4
    # per-device: 1/4 of the global argument (plus rounding slack)
    assert plan.arg_bytes <= nbytes // 4 + 1024
    assert plan.peak_bytes < nbytes          # never charged at global size


@pytest.mark.memory
@pytest.mark.tier1
def test_contributors_carry_sites(rng):
    """Top live-set contributors point at real source sites."""
    from distmlip_tpu.parallel import make_potential_fn

    model, params, graph = _pair_graph(rng)
    pfn = make_potential_fn(model.energy_fn, None)
    jaxpr = jax.make_jaxpr(pfn)(params, graph, graph.positions)
    plan = analyze_memory(jaxpr, top_k=6)
    assert plan.contributors, "a real program has live buffers at peak"
    temps = [c for c in plan.contributors if c.kind == "temp"]
    assert temps, "peak live set of a real program includes temporaries"
    assert any(c.location and str(c.location[0]).endswith(".py")
               for c in temps)
    # rendering is exercised (drives the CLI table + pass messages)
    assert "MiB" in plan.render()


@pytest.mark.memory
@pytest.mark.tier1
def test_aval_bytes():
    x = jnp.ones((3, 5), jnp.float32)
    jaxpr = jax.make_jaxpr(lambda a: a + 1.0)(x)
    aval = jaxpr.jaxpr.invars[0].aval
    assert aval_bytes(aval) == 3 * 5 * 4
    assert aval_bytes(object()) == 0


# ---------------------------------------------------------------------------
# memory_budget pass
# ---------------------------------------------------------------------------


def _toy_program(nbytes_scale=1):
    n = 256 * nbytes_scale

    def f(x, w):
        h = jnp.tanh(x @ w)
        g = jnp.concatenate([h, h], axis=1)
        return g.sum()

    x = jnp.ones((n, 128), jnp.float32)
    w = jnp.ones((128, 128), jnp.float32)
    return jax.make_jaxpr(f)(x, w)


@pytest.mark.memory
@pytest.mark.tier1
def test_memory_budget_pass_overbudget_errors():
    """Seeded over-budget program: ERROR finding + exit code 3."""
    prog = Program(name="seeded_overbudget", jaxpr=_toy_program(),
                   config={"bytes_limit": 64 * 1024})   # 64 KiB budget
    findings = run_passes(prog, get_passes(["memory_budget"]))
    errs = [f for f in findings if f.severity == Severity.ERROR]
    assert len(errs) == 1
    assert errs[0].rule == "over-budget"
    assert "exceeds" in errs[0].message
    assert exit_code(findings) == 3


@pytest.mark.memory
@pytest.mark.tier1
def test_memory_budget_pass_clean_and_infoline():
    """Generous budget: no gate, but the INFO estimate always reports."""
    prog = Program(name="fits", jaxpr=_toy_program(),
                   config={"bytes_limit": 1 << 30})
    findings = run_passes(prog, get_passes(["memory_budget"]))
    assert exit_code(findings) == 0
    infos = [f for f in findings if f.rule == "peak-estimate"]
    assert len(infos) == 1 and "estimated per-device peak" in infos[0].message
    # no budget at all (CPU, no config): INFO only, never an error
    findings = run_passes(Program(name="nolimit", jaxpr=_toy_program()),
                          get_passes(["memory_budget"]))
    assert exit_code(findings) == 0


@pytest.mark.memory
@pytest.mark.tier1
def test_memory_budget_pass_transient_warning():
    """Fits at steady state, but one loop transient owns > half the
    budget: WARNING, not ERROR."""
    carry = jnp.ones((512, 256), jnp.float32)    # 512 KiB

    def step(c, _):
        return jnp.tanh(c), ()

    def f(c0):
        c, _ = jax.lax.scan(step, c0, jnp.arange(4, dtype=jnp.float32))
        return c.sum()

    jaxpr = jax.make_jaxpr(f)(carry)
    plan = analyze_memory(jaxpr)
    limit = int(plan.peak_bytes / 0.8)           # peak = 80% of budget
    prog = Program(name="transient", jaxpr=jaxpr,
                   config={"bytes_limit": limit})
    findings = run_passes(prog, get_passes(["memory_budget"]))
    assert exit_code(findings) == 0
    warns = [f for f in findings if f.severity == Severity.WARNING]
    assert len(warns) == 1 and warns[0].rule == "large-transient"


@pytest.mark.memory
def test_contract_check_cli_budget_exit_codes(rng):
    """The CLI wiring end to end: a tiny --hbm-budget-gb makes a real
    program exit 3; a generous one exits 0."""
    import contract_check as cc

    args = ["--models", "tensornet", "--programs", "energy[tensornet][1x1]",
            "--passes", "memory_budget"]
    assert cc.main(args + ["--hbm-budget-gb", "0.0005"]) == 3
    assert cc.main(args + ["--hbm-budget-gb", "16"]) == 0


# ---------------------------------------------------------------------------
# estimator vs the XLA oracle
# ---------------------------------------------------------------------------


@pytest.mark.memory
@pytest.mark.tier1
def test_estimator_vs_oracle_fast(rng):
    """Fast band check on two cheap-to-compile real programs."""
    from distmlip_tpu.parallel import make_potential_fn, make_total_energy

    model, params, graph = _pair_graph(rng)
    zero = jnp.zeros((3, 3), jnp.float32)
    for fn, a in ((make_total_energy(model.energy_fn, None),
                   (params, graph, graph.positions, zero)),
                  (make_potential_fn(model.energy_fn, None),
                   (params, graph, graph.positions))):
        jaxpr = jax.make_jaxpr(fn)(*a)
        est = analyze_memory(jaxpr).peak_bytes
        oracle = oracle_peak_bytes(jaxpr)
        assert oracle, "CPU XLA must report memory_analysis"
        ratio = est / oracle
        assert ORACLE_BAND[0] <= ratio <= ORACLE_BAND[1], (
            f"estimate {est} vs oracle {oracle}: {ratio:.2f}x out of band")


@pytest.mark.memory
@pytest.mark.slow
def test_estimator_vs_oracle_all_contract_programs():
    """The acceptance criterion: estimated peak within 2x of XLA's
    memory_analysis totals for EVERY contract-check program (22 programs:
    4 models x {(1,1),(2,1),(2,2)} energy/potential/batched + packed
    batch + DeviceMD stepper). One real CPU compile per program — slow
    lane only."""
    import contract_check as cc

    programs = []
    for name in cc.ALL_MODELS:
        cc._trace_model_programs(name, programs)
    cc._trace_packed_batch(programs)
    cc._trace_device_md(programs)
    assert len(programs) == 22

    out_of_band = []
    no_oracle = []
    for prog in programs:
        est = analyze_memory(prog.jaxpr).peak_bytes
        oracle = oracle_peak_bytes(prog.jaxpr)
        if not oracle:
            no_oracle.append(prog.name)
            continue
        ratio = est / oracle
        if not (ORACLE_BAND[0] <= ratio <= ORACLE_BAND[1]):
            out_of_band.append(f"{prog.name}: {ratio:.2f}x "
                               f"(est {est}, oracle {oracle})")
    assert not no_oracle, f"oracle unavailable for {no_oracle}"
    assert not out_of_band, "estimator out of the 2x band:\n" + \
        "\n".join(out_of_band)


# ---------------------------------------------------------------------------
# memory-aware autobatching
# ---------------------------------------------------------------------------


@pytest.mark.memory
@pytest.mark.tier1
def test_bucket_policy_bytes_model():
    from distmlip_tpu.partition import BucketPolicy

    pol = BucketPolicy()
    assert not pol.bytes_calibrated()
    assert pol.estimate_batch_bytes(100) is None   # uncalibrated: no guess
    pol.calibrate_bytes(128, 10 * 2**20)
    assert pol.bytes_calibrated()
    # exact rung: the calibrated value verbatim
    assert pol.estimate_batch_bytes(100) == 10 * 2**20
    # other rungs: worst coefficient scaled up (monotone in cap)
    big = pol.estimate_batch_bytes(1000)
    assert big > 10 * 2**20
    cap = pol.get("nodes", 1000)
    assert big == int(cap * (10 * 2**20 / 128)) + 1
    # worst-per-rung semantics: smaller recalibration never shrinks it
    pol.calibrate_bytes(128, 1 * 2**20)
    assert pol.estimate_batch_bytes(100) == 10 * 2**20
    pol.calibrate_bytes(128, 20 * 2**20)
    assert pol.estimate_batch_bytes(100) == 20 * 2**20


@pytest.mark.memory
@pytest.mark.tier1
def test_bucket_policy_bytes_model_small_batches_stay_conservative():
    """The resident term (params/consts) does not scale with batch size:
    a single LARGE calibration point must not let small batches estimate
    as nearly-free (the under-admission OOM the budget exists to stop)."""
    from distmlip_tpu.partition import BucketPolicy

    pol = BucketPolicy()
    pol.calibrate_bytes(4096, 8 << 30)         # one big rung, 8 GiB
    # single point: the observed peak is a hard floor below it — a
    # never-measured small batch is not assumed cheaper than anything
    # ever measured
    assert pol.estimate_batch_bytes(100) >= 8 << 30
    # two points: affine fit recovers the resident term, so small rungs
    # estimate resident + k*cap instead of either extreme
    pol2 = BucketPolicy()
    resident, k = 6 << 30, 1 << 20             # 6 GiB resident, 1 MiB/atom
    pol2.calibrate_bytes(1024, resident + k * 1024)
    pol2.calibrate_bytes(4096, resident + k * 4096)
    est = pol2.estimate_batch_bytes(100)       # rung 128
    want = resident + k * 128
    assert abs(est - want) <= want * 0.01
    # and it still refuses to dip below the resident term
    assert est > resident
    # the fit runs through the extreme rungs only: an edge-heavy MIDDLE
    # rung's observed peak is a floor for every larger rung — a bigger
    # batch must never estimate cheaper than a measured smaller one
    pol3 = BucketPolicy()
    pol3.calibrate_bytes(128, 50 * 10**6)
    pol3.calibrate_bytes(384, 150 * 10**6)     # edge-heavy outlier
    pol3.calibrate_bytes(1152, 200 * 10**6)
    est_mid = pol3.estimate_batch_bytes(400)   # uncalibrated rung 640
    assert est_mid >= 150 * 10**6
    # the EXACT-rung path applies the same observed-smaller-rung floor:
    # a lightly-calibrated larger rung never undercuts its edge-heavy
    # smaller sibling
    pol4 = BucketPolicy()
    pol4.calibrate_bytes(128, 50 * 10**6)      # edge-heavy small pack
    pol4.calibrate_bytes(384, 10 * 10**6)      # light larger pack
    assert pol4.estimate_batch_bytes(250) >= 50 * 10**6


@pytest.mark.memory
@pytest.mark.tier1
def test_plan_batch_bytes_budget_never_exceeded(rng):
    """The bytes-budget autobatcher NEVER assembles a batch whose
    estimate exceeds the budget — adversarial random streams."""
    from distmlip_tpu.partition import BucketPolicy
    from distmlip_tpu.serve.scheduler import plan_batch

    pol = BucketPolicy()
    pol.calibrate_bytes(128, 4 * 2**20)       # 32 KiB per capacity atom
    local = np.random.default_rng(7)
    budget = 12 * 2**20
    for _ in range(50):
        sizes = local.integers(8, 520, size=local.integers(1, 30)).tolist()
        plan = plan_batch(sizes, policy=pol, max_batch=16,
                          bytes_budget=budget)
        assert plan.take and plan.take[0] == 0     # head never starved
        assert plan.est_bytes is not None
        if len(plan.take) > 1:
            # the core invariant: a MULTI-request batch is never
            # estimated over budget
            assert plan.est_bytes <= budget, (
                f"sizes={sizes} take={plan.take} est={plan.est_bytes}")
        elif plan.est_bytes > budget:
            # over-budget heads are head-only: flagged (fail) when their
            # rung is measured, unflagged solo probes when extrapolated
            assert plan.take == [0]


@pytest.mark.memory
@pytest.mark.tier1
def test_plan_batch_overbudget_head_flagged():
    from distmlip_tpu.partition import BucketPolicy
    from distmlip_tpu.serve.scheduler import plan_batch

    pol = BucketPolicy()
    pol.calibrate_bytes(128, 4 * 2**20)
    # head of 1000 atoms over a 12 MiB budget on an EXTRAPOLATED
    # estimate: head-only solo probe, NOT flagged (its compile will
    # calibrate the rung; flagging guesses could livelock the lane)
    plan = plan_batch([1000, 16, 16], policy=pol, max_batch=8,
                      bytes_budget=12 * 2**20)
    assert plan.take == [0] and not plan.over_budget
    assert plan.est_bytes > 12 * 2**20
    # same head on its own MEASURED rung: flagged — the engine fails it
    pol.calibrate_bytes(pol.get("nodes", 1000), 40 * 2**20)
    assert pol.has_calibrated_rung(1000)
    plan = plan_batch([1000, 16, 16], policy=pol, max_batch=8,
                      bytes_budget=12 * 2**20)
    assert plan.over_budget and plan.take == [0]
    # same stream, no budget: plain fill, never flagged
    plan = plan_batch([1000, 16, 16], policy=pol, max_batch=8)
    assert not plan.over_budget and len(plan.take) > 1


@pytest.mark.memory
@pytest.mark.tier1
def test_plan_batch_bytes_budget_parity_with_fixed_b(rng):
    """A generous budget reproduces the historical fixed-B fill exactly,
    and no budget at all is byte-identical to the pre-budget planner."""
    from distmlip_tpu.partition import BucketPolicy
    from distmlip_tpu.serve.scheduler import plan_batch

    pol = BucketPolicy()
    pol.calibrate_bytes(128, 4 * 2**20)
    local = np.random.default_rng(11)
    for _ in range(30):
        sizes = local.integers(8, 120, size=local.integers(1, 30)).tolist()
        base = plan_batch(sizes, policy=pol, max_batch=8)
        generous = plan_batch(sizes, policy=pol, max_batch=8,
                              bytes_budget=1 << 40)
        assert base.take == generous.take
        assert base.skipped == generous.skipped
        assert base.total_atoms == generous.total_atoms


@pytest.mark.memory
@pytest.mark.tier1
def test_batched_potential_calibrates_and_reports(rng):
    """A fresh compile calibrates the bytes model and the telemetry
    fields; cache hits reuse the bucket's estimate."""
    from distmlip_tpu.calculators import Atoms, BatchedPotential
    from distmlip_tpu.models.pair import PairConfig, PairPotential
    from tests.utils import make_crystal

    model = PairPotential(PairConfig(cutoff=3.2))
    params = model.init(jax.random.PRNGKey(0))
    cart, lattice, species = make_crystal(rng, reps=(2, 2, 2), a=3.6)
    atoms = Atoms(numbers=np.full(len(cart), 14), positions=cart,
                  cell=lattice)
    pot = BatchedPotential(model, params)
    assert pot.hbm_budget_bytes is None       # CPU: no reported limit
    pot.calculate([atoms, atoms.copy()])
    assert pot.last_est_peak_bytes > 0
    assert pot.last_stats["est_peak_bytes"] == pot.last_est_peak_bytes
    assert pot.caps.bytes_calibrated()
    assert pot.estimate_batch_bytes(2 * len(atoms)) > 0
    # warm path (same shapes): the bucket cache still reports the estimate
    first = pot.last_est_peak_bytes
    pot.calculate([atoms, atoms.copy()])
    assert pot.last_est_peak_bytes == first
    # memory_model=False: no calibration trace at all
    pot2 = BatchedPotential(model, params, memory_model=False)
    pot2.calculate([atoms])
    assert pot2.last_est_peak_bytes == 0
    assert not pot2.caps.bytes_calibrated()


@pytest.mark.memory
@pytest.mark.serve
@pytest.mark.tier1
def test_serve_engine_overbudget_admission(rng):
    """A structure whose SOLO estimate exceeds the batched lane's HBM
    budget is rejected at submit (both admission modes); a generous
    budget admits and serves it."""
    from distmlip_tpu.calculators import Atoms, BatchedPotential
    from distmlip_tpu.models.pair import PairConfig, PairPotential
    from distmlip_tpu.serve import ServeEngine, ServeRejected
    from tests.utils import make_crystal

    model = PairPotential(PairConfig(cutoff=3.2))
    params = model.init(jax.random.PRNGKey(0))
    cart, lattice, species = make_crystal(rng, reps=(2, 2, 2), a=3.6)
    atoms = Atoms(numbers=np.full(len(cart), 14), positions=cart,
                  cell=lattice)
    pot = BatchedPotential(model, params)
    pot.calculate([atoms])                     # calibrate the bytes model
    est = pot.estimate_batch_bytes(len(atoms))
    assert est and est > 0

    # budget below the solo estimate: reject in BOTH admission modes
    for admission in ("reject", "block"):
        pot.hbm_budget_bytes = est // 2
        eng = ServeEngine(pot, admission=admission, start=False)
        with pytest.raises(ServeRejected, match="HBM budget"):
            eng.submit(atoms)
        assert eng.stats.rejected == 1
        eng.close()

    # generous budget: admitted and served
    pot.hbm_budget_bytes = est * 4
    with ServeEngine(pot) as eng:
        res = eng.submit(atoms).result(timeout=60)
        assert np.isfinite(res["energy"])
    # oversized structures are exempt (they ride the fallback lane — and
    # with none configured they fail with the routing error, not a
    # ServeRejected admission error)
    pot.hbm_budget_bytes = est // 2
    eng = ServeEngine(pot, max_batch_atoms=4, start=True)
    fut = eng.submit(atoms)
    with pytest.raises(ValueError, match="max_batch_atoms"):
        fut.result(timeout=60)
    eng.close()


@pytest.mark.memory
@pytest.mark.serve
@pytest.mark.tier1
def test_serve_engine_overbudget_head_fails_not_dispatches(rng):
    """The pre-calibration admission race: a request admitted before the
    budget/bytes model existed and later becoming an over-budget queue
    head is FAILED by the dispatcher, never run as an over-budget
    batch."""
    from distmlip_tpu.calculators import Atoms, BatchedPotential
    from distmlip_tpu.models.pair import PairConfig, PairPotential
    from distmlip_tpu.serve import ServeEngine, ServeRejected
    from tests.utils import make_crystal

    model = PairPotential(PairConfig(cutoff=3.2))
    params = model.init(jax.random.PRNGKey(0))
    cart, lattice, species = make_crystal(rng, reps=(2, 2, 2), a=3.6)
    atoms = Atoms(numbers=np.full(len(cart), 14), positions=cart,
                  cell=lattice)
    pot = BatchedPotential(model, params)
    pot.calculate([atoms])                     # calibrate the bytes model
    est = pot.estimate_batch_bytes(len(atoms))
    assert pot.hbm_budget_bytes is None
    eng = ServeEngine(pot, start=False)
    fut = eng.submit(atoms)                    # admitted: no budget yet
    pot.hbm_budget_bytes = est // 2            # budget appears afterwards
    eng.start()
    with pytest.raises(ServeRejected, match="HBM budget"):
        fut.result(timeout=60)
    # accounting: the request WAS accepted, so it is a failure, not a
    # (second) submit-time reject — rejected+failed must not double-count
    assert eng.stats.failed == 1
    assert eng.stats.rejected == 0
    eng.close()


# ---------------------------------------------------------------------------
# telemetry report: drift flag only with measured stats
# ---------------------------------------------------------------------------


@pytest.mark.memory
@pytest.mark.tier1
def test_report_hbm_drift_needs_measured_stats():
    from distmlip_tpu.telemetry import StepRecord
    from distmlip_tpu.telemetry.report import aggregate

    def rec(step, est, mem):
        return StepRecord(step=step, kind="batched_calculate",
                          timings={"total_s": 0.1},
                          est_peak_bytes=est, device_memory=mem)

    # CPU-style records: estimates but NO measured stats -> never flagged
    rep = aggregate([rec(i, 50 * 2**20, {}) for i in range(4)])
    assert not any(a.kind == "hbm_estimator_drift" for a in rep.anomalies)
    assert rep.counters.get("max_est_peak_bytes") == 50 * 2**20
    assert "hbm_estimator_ratio" not in rep.counters

    # measured stats present and wildly off the estimate -> flagged
    mem = {"dev0_bytes_in_use": 2**20, "dev0_peak_bytes_in_use": 2**20,
           "dev0_bytes_limit": 2**30}
    rep = aggregate([rec(i, 50 * 2**20, dict(mem)) for i in range(4)])
    assert any(a.kind == "hbm_estimator_drift" for a in rep.anomalies)
    assert rep.counters["hbm_estimator_ratio"] == pytest.approx(50.0)
    assert "hbm:" in rep.render()

    # measured stats in band -> ratio reported, no anomaly
    mem_ok = {"dev0_bytes_in_use": 40 * 2**20,
              "dev0_peak_bytes_in_use": 60 * 2**20,
              "dev0_bytes_limit": 2**30}
    rep = aggregate([rec(i, 50 * 2**20, dict(mem_ok)) for i in range(4)])
    assert not any(a.kind == "hbm_estimator_drift" for a in rep.anomalies)
    assert rep.counters["hbm_estimator_ratio"] == pytest.approx(50 / 60)
    assert rep.counters["max_hbm_used_frac"] == pytest.approx(40 / 1024)

    # LOW ratios never flag: peak_bytes_in_use is a process-lifetime
    # high-water mark, so on a mixed run a tiny batched program measured
    # against an earlier big phase's mark proves nothing
    mem_big = {"dev0_bytes_in_use": 2**20,
               "dev0_peak_bytes_in_use": 100 * 2**20,
               "dev0_bytes_limit": 2**30}
    rep = aggregate([rec(i, 1 * 2**20, dict(mem_big)) for i in range(4)])
    assert not any(a.kind == "hbm_estimator_drift" for a in rep.anomalies)
    assert rep.counters["hbm_estimator_ratio"] == pytest.approx(0.01)


@pytest.mark.memory
@pytest.mark.tier1
def test_utils_memory_shared_implementation():
    """The dedup satellite: calculator + report + planner all consume the
    ONE utils/memory implementation."""
    import distmlip_tpu.calculators.calculator as calc_mod
    from distmlip_tpu.utils.memory import (device_bytes_limit,
                                           device_memory_stats,
                                           hbm_usage_frac,
                                           measured_peak_bytes)

    assert calc_mod._hbm_usage_frac is hbm_usage_frac
    assert calc_mod._device_memory_stats is device_memory_stats
    stats = {"dev0_bytes_in_use": 80, "dev0_bytes_limit": 100,
             "dev1_bytes_in_use": 10, "dev1_bytes_limit": 50,
             "dev1_peak_bytes_in_use": 33}
    assert hbm_usage_frac(stats) == pytest.approx(0.8)
    assert device_bytes_limit(stats) == 50
    assert measured_peak_bytes(stats) == 33
    assert hbm_usage_frac({}) is None
    assert device_bytes_limit({}) is None
    assert measured_peak_bytes({}) is None
    # CPU: live lookup degrades to "nothing reported", never raises
    assert device_memory_stats() == {}


@pytest.mark.memory
@pytest.mark.tier1
def test_predictive_prefetch_guard(rng, monkeypatch):
    """The HBM prefetch guard is predictive where a bytes_limit exists:
    high occupancy with a TINY estimated build no longer vetoes; a big
    estimated build does."""
    import distmlip_tpu.calculators.calculator as calc_mod
    import distmlip_tpu.utils.memory as um
    from distmlip_tpu.calculators import Atoms, DistPotential
    from distmlip_tpu.models.pair import PairConfig, PairPotential
    from tests.utils import make_crystal

    model = PairPotential(PairConfig(cutoff=3.2))
    params = model.init(jax.random.PRNGKey(0))
    cart, lattice, species = make_crystal(rng, reps=(3, 2, 2), a=3.6)
    atoms = Atoms(numbers=np.full(len(cart), 14), positions=cart,
                  cell=lattice)

    def run(limit):
        # device_rebuild=False: the on-device refresh path would skip
        # speculative host builds entirely (by design), and this test is
        # about the HBM guard on the host-prefetch path
        pot = DistPotential(model, params, num_partitions=1, skin=0.6,
                            prefetch_frac=0.0, device_rebuild=False)
        pot.calculate(atoms)
        moved = atoms.copy()
        moved.positions = moved.positions + 0.02
        pot.calculate(moved)       # warm path; prefetch decision happens
        return pot

    # occupancy 0.6 > 1/3 would historically always veto
    monkeypatch.setattr(calc_mod, "_hbm_usage_frac", lambda s=None: 0.6)
    # predictive: huge limit -> the graph adds ~0 frac -> NO veto
    monkeypatch.setattr(um, "device_bytes_limit", lambda s=None: 1 << 50)
    pot = run(1 << 50)
    assert pot.prefetch_skipped_hbm == 0
    assert pot._prefetch is not None
    pot.close()
    # predictive: tiny limit -> the build residency blows the ceiling
    monkeypatch.setattr(um, "device_bytes_limit", lambda s=None: 1024)
    pot = run(1024)
    assert pot.prefetch_skipped_hbm >= 1
    assert pot._prefetch is None
    pot.close()


@pytest.mark.memory
@pytest.mark.tier1
def test_memory_audit_cli_smoke(rng):
    """memory_audit CLI: table + budget gate exit codes (pair-free fast
    path rides the tensornet 1x1 energy program)."""
    import memory_audit as ma

    args = ["--models", "tensornet", "--programs",
            "energy[tensornet][1x1]"]
    assert ma.main(args) == 0
    assert ma.main(args + ["--budget-gb", "0.0005"]) == 3
    assert ma.main(["--budget-gb", "-1"]) == 2
    assert ma.main(["--models", "nope"]) == 2
