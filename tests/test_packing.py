"""Cost-model-driven batch packing (distmlip_tpu/train/packing.py).

The load-bearing invariants, each pinned:

- the serving pack stats and the training loader compute padding waste
  through ONE shared implementation (``partition.slot_waste_frac``), and
  the analytic prediction equals the built pack's measured number;
- the tiered plan is seed-stable: same ``(seed, epoch)`` => byte-identical
  micro-batches, and a mid-epoch resume ACROSS a tier boundary is bitwise
  identical to the uninterrupted run;
- long-tail adversarial: one giant structure must not inflate every
  batch's caps — and on a lognormal >= 200-structure dataset the
  cost-model loader cuts padding waste by >= 2x vs the frozen single cap
  (the ISSUE's acceptance bar);
- equal-loss parity: cost-model packing reorders structures WITHIN an
  accumulation window, and the summed gradient is order-independent, so
  the loss trajectory matches naive packing to fp32 roundoff;
- compile discipline: a whole tiered run compiles at most one train-step
  executable per tier;
- the tiered train-step programs trace clean through every registered
  contract pass with the same config (no per-tier contract drift);
- tools/pack_audit.py is CI-pinned: exit 0 under a generous waste bound,
  exit 3 when the bound (or the HBM budget) is violated.
"""

import importlib.util
import os
import sys

import jax
import numpy as np
import optax
import pytest

from distmlip_tpu import geometry
from distmlip_tpu.calculators import Atoms
from distmlip_tpu.models.tensornet import TensorNet, TensorNetConfig
from distmlip_tpu.partition import (fixed_caps_for_batches, graph_live_slots,
                                    pack_structures, packed_stats,
                                    slot_waste_frac)
from distmlip_tpu.train import (PackedBatchLoader, Sample, TrainConfig,
                                Trainer, assign_tiers, init_train_state,
                                make_accum_train_step, plan_epoch,
                                plan_epoch_naive, predicted_plan_waste,
                                structure_needs, tier_caps)
from distmlip_tpu.train.packing import CostCensus, default_cost

pytestmark = pytest.mark.train

TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")
UNIT = np.array([[0, 0, 0], [0.5, 0.5, 0], [0.5, 0, 0.5], [0, 0.5, 0.5]])
CFG = TensorNetConfig(num_species=3, units=8, num_rbf=4, num_layers=1,
                      cutoff=3.2)


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(TOOLS, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def species_fn(z):
    return (z - 1).astype(np.int32)


def make_samples(rng, n, reps, n_species=3, a=3.6):
    frac, lat = geometry.make_supercell(UNIT, np.eye(3) * a, reps)
    out = []
    for _ in range(n):
        cart = geometry.frac_to_cart(frac, lat) + rng.normal(
            0, 0.05, (len(frac), 3))
        atoms = Atoms(numbers=rng.integers(1, 1 + n_species, len(frac)),
                      positions=cart, cell=lat)
        out.append(Sample(
            atoms, float(rng.normal()),
            rng.normal(0, 0.1, (len(frac), 3)).astype(np.float32)))
    return out


@pytest.fixture(scope="module")
def longtail_samples():
    """8 small + 4 large structures — two clear tiers."""
    rng = np.random.default_rng(7)
    return make_samples(rng, 8, (1, 1, 1)) + make_samples(rng, 4, (2, 2, 2))


def _loader(samples, **kw):
    kw.setdefault("micro_batch_size", 2)
    kw.setdefault("species_fn", species_fn)
    kw.setdefault("seed", 11)
    kw.setdefault("prefetch", 0)
    return PackedBatchLoader(samples, CFG.cutoff, **kw)


# ---------------------------------------------------------------------------
# one waste definition
# ---------------------------------------------------------------------------


@pytest.mark.tier1
def test_waste_shared_implementation(longtail_samples):
    """Serving stats, the training meta AND the analytic prediction all
    route through slot_waste_frac — the three must agree exactly."""
    batch = longtail_samples[:3]
    graph, host = pack_structures([s.atoms for s in batch], CFG.cutoff,
                                  species_fn=species_fn)
    stats = packed_stats(graph, len(batch))
    live, slots = graph_live_slots(graph)
    assert stats["padding_waste_frac"] == slot_waste_frac(live, slots)

    # the loader's per-step meta equals the mean of its packs' stats,
    # and the packing module predicts the identical number from the
    # needs census alone (same caps, same census -> same waste)
    ld = _loader(longtail_samples, accum_steps=2,
                 packing="cost_model", num_tiers=2)
    plan = ld.epoch_plan(0)
    b = ld.next_batch()
    step0 = plan[0]
    predicted = predicted_plan_waste(ld.needs, [step0], ld.tier_caps,
                                     batch_parts=1)
    assert b.meta["padding_waste_frac"] == pytest.approx(predicted,
                                                         abs=1e-12)
    ld.close()


@pytest.mark.tier1
def test_census_and_default_cost(longtail_samples):
    needs = structure_needs([s.atoms for s in longtail_samples],
                            CFG.cutoff)
    census = CostCensus.from_needs(needs)
    assert len(census.costs) == len(longtail_samples)
    # edges dominate the default cost
    assert default_cost({"edges": 100, "nodes": 10}) == pytest.approx(101.0)
    assert census.skew() > 1.5  # the long tail is visible
    assert "cost census" in census.render()


# ---------------------------------------------------------------------------
# tier selection: the long-tail adversarial case
# ---------------------------------------------------------------------------


@pytest.mark.tier1
def test_giant_structure_does_not_inflate_small_tier(longtail_samples):
    """One giant structure must only inflate the windows that contain it:
    the small tier's frozen caps stay far below the single-cap loader's."""
    ld_naive = _loader(longtail_samples)
    ld_cost = _loader(longtail_samples, packing="cost_model", num_tiers=2)
    naive_caps = ld_naive.caps.as_dict()
    small_caps = ld_cost.tier_caps[0].as_dict()
    big_caps = ld_cost.tier_caps[max(ld_cost.tier_caps)].as_dict()
    assert small_caps["edges"] < naive_caps["edges"] / 2
    assert big_caps["edges"] <= naive_caps["edges"]
    # tier membership: every small structure in tier 0, giants on top
    sizes = np.array([len(s.atoms.positions) for s in longtail_samples])
    assert set(np.asarray(ld_cost.tier_of)[sizes == sizes.min()]) == {0}
    ld_naive.close()
    ld_cost.close()


@pytest.mark.tier1
def test_assign_tiers_min_members_and_ties():
    # 15 equal + 1 giant, min_members=4: the giant cannot claim its own
    # tier — it folds into a >= 4-member top tier
    costs = np.array([10.0] * 15 + [1000.0])
    tier_of, thr = assign_tiers(costs, 3, min_members=4)
    assert tier_of[-1] == max(tier_of)
    top = int(np.sum(tier_of == max(tier_of)))
    assert top >= 4
    # all-equal costs: one tier, no spurious boundaries
    tier_of, thr = assign_tiers(np.full(12, 5.0), 3, min_members=2)
    assert set(tier_of) == {0} and thr == [5.0]


@pytest.mark.tier1
def test_longtail_lognormal_waste_reduction_2x():
    """The acceptance bar: on a lognormal long-tail dataset of >= 200
    structures, cost-model packing cuts predicted padding waste >= 2x vs
    the frozen single-cap loader (the same caps/census arithmetic the
    loader packs with — test_waste_shared_implementation pins predicted
    == measured)."""
    pack_audit = _load_tool("pack_audit")
    samples = pack_audit.synth_longtail_samples(
        200, seed=5, mu=3.0, sigma=1.0, min_atoms=4, max_atoms=600)
    needs = structure_needs([s.atoms for s in samples], 3.5)
    census = CostCensus.from_needs(needs)
    B = 8
    tier_of, _thr = assign_tiers(census.costs, 3, min_members=B)
    caps = tier_caps(needs, tier_of, B, costs=census.costs)
    naive_caps = fixed_caps_for_batches(needs, B)
    plan = plan_epoch(census.costs, tier_of, seed=5, epoch=0,
                      micro_batch_size=B)
    naive_plan = plan_epoch_naive(len(needs), seed=5, epoch=0,
                                  micro_batch_size=B)
    w_cost = predicted_plan_waste(needs, plan, caps)
    w_naive = predicted_plan_waste(needs, naive_plan, {0: naive_caps})
    assert w_naive >= 2.0 * w_cost, (w_naive, w_cost)


@pytest.mark.tier1
def test_edge_balance_beats_naive(longtail_samples):
    """The bin-packer's micro-batches carry balanced edge totals where
    the permutation slicer's do not."""
    needs = structure_needs([s.atoms for s in longtail_samples],
                            CFG.cutoff)
    census = CostCensus.from_needs(needs)

    def window_spread(plan):
        worst = 1.0
        for step in plan:
            tots = [sum(census.costs[list(m)]) for m in step.micro]
            if max(tots) > 0:
                worst = min(worst, min(tots) / max(tots))
        return worst

    tier_of, _ = assign_tiers(census.costs, 1, min_members=4)
    cost_plan = plan_epoch(census.costs, tier_of, seed=3, epoch=0,
                           micro_batch_size=2, accum_steps=2)
    naive_plan = plan_epoch_naive(len(needs), seed=3, epoch=0,
                                  micro_batch_size=2, accum_steps=2)
    assert window_spread(cost_plan) >= window_spread(naive_plan)
    assert window_spread(cost_plan) > 0.5


# ---------------------------------------------------------------------------
# determinism + resume
# ---------------------------------------------------------------------------


@pytest.mark.tier1
def test_tiered_loader_seed_stable_replay(longtail_samples):
    """Same (seed, epoch) => byte-identical micro-batches, across an
    epoch boundary, fresh loader or repositioned cursor."""
    ld1 = _loader(longtail_samples, packing="cost_model", num_tiers=2)
    ld2 = _loader(longtail_samples, packing="cost_model", num_tiers=2)
    batches = []
    for _ in range(ld1.steps_per_epoch + 2):  # crosses the epoch edge
        b1, b2 = ld1.next_batch(), ld2.next_batch()
        batches.append(b1)
        for x, y in zip(jax.tree.leaves((b1.graphs, b1.targets)),
                        jax.tree.leaves((b2.graphs, b2.targets))):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
        assert b1.meta["tier"] == b2.meta["tier"]
    # reposition mid-epoch and replay batch 1 exactly
    ld2.set_state({"seed": 11, "epoch": 0, "step": 1})
    b1r = ld2.next_batch()
    for x, y in zip(jax.tree.leaves(batches[1].graphs),
                    jax.tree.leaves(b1r.graphs)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    # the per-epoch shuffle is live: epoch 1's plan differs from epoch 0's
    assert ld1.epoch_plan(0) != ld1.epoch_plan(1)
    ld1.close()
    ld2.close()


@pytest.mark.tier1
def test_tiered_cursor_carries_tier_and_validates(longtail_samples):
    ld = _loader(longtail_samples, packing="cost_model", num_tiers=2)
    st = ld.state()
    assert st["tier"] == ld.epoch_plan(0)[0].tier
    # a cursor whose tier contradicts the recomputed plan is REJECTED
    # (dataset/seed/tiering drifted => resume would not be bitwise)
    other = 1 - st["tier"]
    with pytest.raises(ValueError, match="tier mismatch"):
        ld.set_state({**st, "tier": other})
    ld.close()


@pytest.mark.tier1
def test_trainer_resume_bitwise_across_tier_boundary(longtail_samples,
                                                     tmp_path):
    """The PR 10 bitwise-resume contract extended to the tiered loader:
    save mid-epoch, continue across a tier boundary, restore into a fresh
    Trainer — losses and final params identical to the uninterrupted run."""
    model = TensorNet(CFG)
    params = model.init(jax.random.PRNGKey(0))

    def trainer():
        return Trainer(
            model.energy_fn, params, optax.adam(3e-3), longtail_samples,
            CFG.cutoff, micro_batch_size=2,
            config=TrainConfig(ema_decay=0.99),
            checkpoint_dir=str(tmp_path / "ckpts"),
            loader_kwargs={"species_fn": species_fn, "seed": 13,
                           "packing": "cost_model", "num_tiers": 2})

    t1 = trainer()
    tiers = [t1.loader.epoch_plan(0)[i].tier
             for i in range(t1.steps_per_epoch)]
    assert len(set(tiers)) == 2  # both tiers appear within the epoch
    for _ in range(2):
        t1.train_step()
    path = t1.save_checkpoint()
    cursor = dict(t1.loader.state())
    cont = [t1.train_step()["loss"] for _ in range(3)]
    end1 = np.asarray(jax.flatten_util.ravel_pytree(t1.state.params)[0])
    t1.close()

    t2 = trainer()
    t2.restore(path)
    assert t2.loader.state() == cursor
    cont2 = [t2.train_step()["loss"] for _ in range(3)]
    end2 = np.asarray(jax.flatten_util.ravel_pytree(t2.state.params)[0])
    t2.close()
    assert cont == cont2, (cont, cont2)
    np.testing.assert_array_equal(end1, end2)


# ---------------------------------------------------------------------------
# equal-loss parity + compile discipline
# ---------------------------------------------------------------------------


@pytest.mark.tier1
def test_equal_loss_parity_within_accum_window():
    """With B * A = N the whole dataset is ONE optimizer step; cost-model
    packing only reorders which structures share a micro-batch, and the
    summed gradient over the window is order-independent — the two loss
    trajectories match to fp32 roundoff."""
    rng = np.random.default_rng(3)
    samples = make_samples(rng, 8, (2, 2, 1))
    model = TensorNet(CFG)
    params = model.init(jax.random.PRNGKey(9))
    opt = optax.sgd(0.05)
    outs = {}
    for mode, kw in (("naive", {}),
                     ("cost", {"packing": "cost_model", "num_tiers": 1})):
        ld = _loader(samples, micro_batch_size=2, accum_steps=4,
                     seed=5, **kw)
        state = init_train_state(opt, params, None, TrainConfig(), seed=0)
        step = make_accum_train_step(model.energy_fn, opt, None,
                                     TrainConfig(accum_steps=4),
                                     donate=False)
        losses = []
        for _ in range(3):
            b = ld.next_batch()
            state, m = step(state, b.graphs, b.targets)
            losses.append(float(m["loss"]))
        outs[mode] = (losses, state)
        ld.close()
    ln, lc = outs["naive"][0], outs["cost"][0]
    np.testing.assert_allclose(ln, lc, rtol=1e-4)
    fa = np.asarray(
        jax.flatten_util.ravel_pytree(outs["naive"][1].params)[0])
    fb = np.asarray(
        jax.flatten_util.ravel_pytree(outs["cost"][1].params)[0])
    assert np.abs(fa - fb).max() <= 1e-5 * max(np.abs(fb).max(), 1.0)


@pytest.mark.tier1
def test_compile_count_bounded_by_tiers(longtail_samples):
    """A full tiered epoch compiles at most one step executable per tier."""
    model = TensorNet(CFG)
    params = model.init(jax.random.PRNGKey(0))
    t = Trainer(model.energy_fn, params, optax.adam(1e-3),
                longtail_samples, CFG.cutoff, micro_batch_size=2,
                loader_kwargs={"species_fn": species_fn, "seed": 2,
                               "packing": "cost_model", "num_tiers": 2})
    assert t.loader.num_tiers == 2
    assert sorted(t.tier_peak_bytes) == sorted(t.loader.tier_caps)
    assert all(v > 0 for v in t.tier_peak_bytes.values())
    t.fit(epochs=1)
    assert 0 < t.compile_count <= t.loader.num_tiers
    t.close()


# ---------------------------------------------------------------------------
# telemetry: packing section + padding_waste_dominant anomaly
# ---------------------------------------------------------------------------


@pytest.mark.tier1
def test_report_packing_section_and_waste_anomaly():
    from distmlip_tpu.telemetry import TrainRecord
    from distmlip_tpu.telemetry.report import aggregate

    good = [TrainRecord(step=i, loss=1.0, padding_waste_frac=0.2,
                        edge_balance=0.9, tier=i % 2,
                        timings={"total_s": 0.1}) for i in range(4)]
    rep = aggregate(good)
    t = rep.counters["training"]
    assert t["mean_padding_waste_frac"] == pytest.approx(0.2)
    assert t["n_tiers"] == 2 and t["min_edge_balance"] == 0.9
    assert "packing: waste mean=0.20" in rep.render()
    assert not any(a.kind == "padding_waste_dominant"
                   for a in rep.anomalies)

    bad = [TrainRecord(step=i, loss=1.0, padding_waste_frac=0.8,
                       timings={"total_s": 0.1}) for i in range(6)]
    rep2 = aggregate(bad)
    assert any(a.kind == "padding_waste_dominant" for a in rep2.anomalies)
    # JSONL roundtrip: packing fields survive reparse as StepRecord
    from distmlip_tpu.telemetry import StepRecord
    back = StepRecord.from_json(good[1].to_json())
    assert TrainRecord.training_field(back, "edge_balance") == 0.9
    assert TrainRecord.training_field(back, "tier") == 1


# ---------------------------------------------------------------------------
# tiered contract programs + pack_audit CLI
# ---------------------------------------------------------------------------


@pytest.mark.tier1
def test_tiered_train_step_contracts():
    """Both tier executables trace clean through every registered pass
    under the SAME config — no per-tier contract drift."""
    from distmlip_tpu.analysis import error_count, get_passes, run_passes

    cc = _load_tool("contract_check")
    programs = []
    cc._trace_train_step_tiers(programs)
    names = sorted(p.name for p in programs)
    assert names == ["train_step[tensornet][1x1][tier0]",
                     "train_step[tensornet][1x1][tier1]"]
    configs = [p.config for p in programs]
    assert configs[0] == configs[1]  # shared contract, shapes aside
    for prog in programs:
        findings = run_passes(prog, get_passes())
        assert error_count(findings) == 0, [f.render() for f in findings]


@pytest.mark.tier1
def test_pack_audit_cli(capsys):
    pack_audit = _load_tool("pack_audit")
    args = ["--n", "30", "--micro-batch", "4", "--tiers", "2",
            "--max-atoms", "120", "--seed", "3"]
    # generous bound, HBM priced and within budget: clean exit
    assert pack_audit.main(
        args + ["--hbm-budget-gb", "64", "--json"]) == 0
    out = capsys.readouterr().out
    import json

    rep = json.loads(out)
    assert rep["predicted_waste_naive"] >= rep["predicted_waste_packed"]
    assert all("est_peak_bytes" in t and t["est_peak_bytes"] > 0
               for t in rep["tiers"])
    # impossible waste bound: exit 3 with the violation named
    assert pack_audit.main(
        args + ["--no-price-hbm", "--waste-bound", "0.0001"]) == 3
    assert "VIOLATION" in capsys.readouterr().out
    # usage error
    assert pack_audit.main(["--n", "2", "--micro-batch", "8"]) == 2
