"""Compiler/device observability plane: compile telemetry, device-time
attribution, roofline accounting, perf-regression baseline gate.

The contracts under test:

- every compile point feeds ONE event stream (``obs/profiling.py``) with
  the fresh-vs-AOT split: a BatchedPotential bucket compile records
  ``fresh``; a replica restarted onto a warm AOT cache records ``aot``
  rehydrates and keeps ``compile_count == 0`` (the restart gate);
- trace-based and cost-model attribution bucket identically — a
  ``named_scope`` beats the op name for both sources, and a synthetic
  Perfetto capture and a traced jaxpr produce the same category keys;
- ``jaxpr_flop_estimate`` is dot_general-exact; roofline rows derive
  intensity/achieved/MFU without a chip, and record-derived rows
  tolerate mixed rounds where only some records carry FLOP estimates;
- ``tools/perf_gate.py`` classifies identity rounds ok (exit 0),
  synthetic regressions as regressions (exit 3), respects the
  allow-list, rejects malformed baselines (exit 2), and the
  ``--check-schema`` self-test catches a comparator that stops doing
  any of that.
"""

import json
import os
import sys

import numpy as np
import pytest

from distmlip_tpu import geometry
from distmlip_tpu.calculators import Atoms, BatchedPotential
from distmlip_tpu.models import PairConfig, PairPotential
from distmlip_tpu.obs import Observability, profiling, uninstall
from distmlip_tpu.obs.attribution import (CATEGORIES, ScopeBreakdown,
                                          attribute, attribute_cost_model,
                                          attribute_trace, classify)
from distmlip_tpu.obs.roofline import (RooflineRow, bytes_touched,
                                       format_roofline_table,
                                       jaxpr_flop_estimate,
                                       rows_from_records)
from distmlip_tpu.telemetry import StepRecord

pytestmark = [pytest.mark.profiling, pytest.mark.tier1]

REPO = os.path.join(os.path.dirname(__file__), "..")


def make_atoms(n=16, seed=0, a=3.6):
    rng = np.random.default_rng(seed)
    unit = np.array([[0, 0, 0], [0.5, 0.5, 0], [0.5, 0, 0.5], [0, 0.5, 0.5]])
    reps = (2, 2, 1) if n >= 16 else (1, 1, 1)
    frac, lattice = geometry.make_supercell(unit, np.eye(3) * a, reps)
    cart = geometry.frac_to_cart(frac, lattice) + rng.normal(
        0, 0.02, (len(frac), 3))
    return Atoms(numbers=np.full(len(cart), 14), positions=cart,
                 cell=lattice)


@pytest.fixture(scope="module")
def pair():
    model = PairPotential(PairConfig(cutoff=4.0))
    return model, model.init()


@pytest.fixture(autouse=True)
def _fresh_compile_log():
    profiling.reset_compile_log()
    yield
    profiling.reset_compile_log()
    uninstall()


# ---------------------------------------------------------------------------
# compile telemetry: the event log + metrics registry
# ---------------------------------------------------------------------------


def test_compile_log_records_and_resets():
    profiling.record_compile(site="test", kind=profiling.KIND_FRESH,
                             wall_s=0.25, bucket_key="n=64/e=256/B=1")
    profiling.record_compile(site="test", kind=profiling.KIND_AOT,
                             wall_s=0.01, executable_bytes=1234)
    evs = profiling.compile_events()
    assert [e.kind for e in evs] == ["fresh", "aot"]
    assert evs[0].bucket_key == "n=64/e=256/B=1"
    assert evs[1].executable_bytes == 1234
    assert profiling.compile_counts() == {"fresh": 1, "aot": 1}
    d = evs[0].as_dict()
    assert d["site"] == "test" and d["wall_s"] == 0.25
    profiling.reset_compile_log()
    assert profiling.compile_counts() == {}


def test_compile_events_feed_metrics_registry():
    hub = Observability.enable()
    profiling.record_compile(site="batched_bucket",
                             kind=profiling.KIND_FRESH, wall_s=0.5)
    profiling.record_compile(site="aot_dispatch",
                             kind=profiling.KIND_AOT, wall_s=0.002)
    text = hub.metrics.render()
    assert ('distmlip_compiles_total{site="batched_bucket",kind="fresh"} 1'
            in text)
    assert ('distmlip_compiles_total{site="aot_dispatch",kind="aot"} 1'
            in text)
    assert "distmlip_compile_seconds_bucket" in text


def test_record_compile_survives_broken_registry(monkeypatch):
    """A broken metrics backend must not fail a compile that succeeded."""

    class Boom:
        def histogram(self, *a, **k):
            raise RuntimeError("metrics backend down")

        def counter(self, *a, **k):
            raise RuntimeError("metrics backend down")

    from distmlip_tpu.obs import runtime as obsrt

    monkeypatch.setattr(obsrt, "metrics", lambda: Boom())
    ev = profiling.record_compile(site="x", kind="fresh", wall_s=0.1)
    assert ev.wall_s == 0.1
    assert profiling.compile_counts() == {"fresh": 1}


def test_batched_bucket_compile_records_fresh(pair):
    model, params = pair
    pot = BatchedPotential(model, params)
    pot.calculate([make_atoms(seed=1)])
    counts = profiling.compile_counts()
    assert counts.get("fresh", 0) >= 1
    assert not counts.get("aot", 0)
    # warm repeat (same bucket): no new events
    n0 = len(profiling.compile_events())
    pot.calculate([make_atoms(seed=2)])
    assert len(profiling.compile_events()) == n0


def test_aot_restart_gate_splits_fresh_vs_aot(pair, tmp_path):
    """First potential compiles FRESH and exports; a 'restarted' second
    potential on the same cache dir REHYDRATES: aot events, and the
    restart gate's compile_count == 0 still holds."""
    from distmlip_tpu.fleet import install_aot_cache

    model, params = pair
    cache_dir = str(tmp_path / "aot")
    pot1 = BatchedPotential(model, params)
    install_aot_cache(pot1, cache_dir)
    pot1.calculate([make_atoms(seed=3)])
    counts = profiling.compile_counts()
    assert counts.get("fresh", 0) >= 1
    assert pot1.aot_cache.stats()["saved"] >= 1

    pot2 = BatchedPotential(model, params)
    install_aot_cache(pot2, cache_dir)
    pot2.calculate([make_atoms(seed=4)])  # same shape bucket
    counts = profiling.compile_counts()
    assert counts.get("aot", 0) >= 1, counts
    assert pot2.compile_count == 0        # the restart gate
    assert pot2.aot_cache.stats()["rehydrated"] >= 1
    aot_evs = [e for e in profiling.compile_events() if e.kind == "aot"]
    assert aot_evs[0].executable_bytes > 0


def test_metrics_label_cardinality_cap_overflows_to_other():
    from distmlip_tpu.obs import MetricsRegistry, parse_exposition

    reg = MetricsRegistry(max_label_children=4)
    fam = reg.counter("x_total", "cardinality probe", labels=("k",))
    for i in range(10):
        fam.labels(k=f"v{i}").inc()
    vals = parse_exposition(reg.render())
    assert vals.get('x_total{k="_other"}', 0) == 6.0
    assert vals.get('distmlip_metrics_label_overflow_total'
                    '{metric="x_total"}', 0) == 6.0
    # capped children keep their own identity
    assert vals.get('x_total{k="v0"}') == 1.0


# ---------------------------------------------------------------------------
# device-time attribution: trace + cost-model, one bucketing
# ---------------------------------------------------------------------------


def test_classify_rules_and_scope_priority():
    assert classify("ppermute") == "halo_exchange"
    assert classify("fusion.3", "jit(f)/halo_exchange/add") == "halo_exchange"
    # an author named_scope beats the op name
    assert classify("dot_general", "jit(f)/halo_exchange") == "halo_exchange"
    assert classify("pallas_call") == "pallas_kernel"
    assert classify("scatter-add.1") == "scatter"
    assert classify("transpose", "jit(f)/backward") == "gradient_transpose"
    assert classify("dot_general") == "interior_aggregation"
    assert classify("copy.7") == "other"
    assert set(CATEGORIES) >= {classify("anything"), "halo_exchange"}


def test_attribute_trace_synthetic_capture(tmp_path):
    trace = {"traceEvents": [
        {"ph": "X", "name": "ppermute.1", "dur": 300.0, "args": {}},
        {"ph": "X", "name": "fusion.2", "dur": 500.0,
         "args": {"op_name": "jit(step)/interior_aggregation/dot_general"}},
        {"ph": "X", "name": "scatter-add.3", "dur": 200.0, "args": {}},
        {"ph": "M", "name": "process_name"},          # metadata: skipped
        {"ph": "X", "name": "thread_sort_index"},     # noise: skipped
        {"ph": "X", "name": "zero", "dur": 0.0},      # no duration: skipped
    ]}
    bd = attribute_trace(trace, program="step")
    assert bd.source == "trace" and bd.n_events == 3
    assert bd.total_s == pytest.approx(1e-3)
    assert bd.by_category["halo_exchange"] == pytest.approx(300e-6)
    assert bd.by_category["interior_aggregation"] == pytest.approx(500e-6)
    assert bd.fraction("scatter") == pytest.approx(0.2)
    # path round-trip (the offline-parser entry point)
    p = tmp_path / "capture.json"
    p.write_text(json.dumps(trace))
    bd2 = attribute_trace(str(p))
    assert bd2.by_category == bd.by_category
    assert "halo_exchange" in bd.render()


def test_attribute_cost_model_apportions_measured_total():
    import jax
    import jax.numpy as jnp

    def step(x, w):
        with jax.named_scope("halo_exchange"):
            h = jnp.roll(x, 1, axis=0) + x
        with jax.named_scope("interior_aggregation"):
            y = h @ w
        return y.sum()

    jaxpr = jax.make_jaxpr(step)(jnp.ones((8, 4)), jnp.ones((4, 4)))
    bd = attribute_cost_model(jaxpr, total_s=2.0, program="step")
    assert bd.source == "cost_model" and bd.n_events > 0
    # the split is an estimate; the total is real
    assert sum(bd.by_category.values()) == pytest.approx(2.0)
    assert bd.by_category.get("interior_aggregation", 0.0) > 0
    assert bd.total_s == 2.0
    d = bd.as_dict()
    assert d["program"] == "step" and d["by_category"] == bd.by_category


def test_attribute_entry_point_prefers_trace_falls_back():
    trace = {"traceEvents": [
        {"ph": "X", "name": "ppermute", "dur": 100.0}]}
    assert attribute(1.0, trace=trace).source == "trace"
    empty = {"traceEvents": []}
    import jax
    import jax.numpy as jnp

    jaxpr = jax.make_jaxpr(lambda x: (x * x).sum())(jnp.ones(4))
    assert attribute(1.0, trace=empty, jaxpr=jaxpr).source == "cost_model"
    bd = attribute(1.0)
    assert isinstance(bd, ScopeBreakdown) and bd.n_events == 0


# ---------------------------------------------------------------------------
# roofline accounting
# ---------------------------------------------------------------------------


def test_jaxpr_flop_estimate_dot_general_exact():
    import jax
    import jax.numpy as jnp

    jaxpr = jax.make_jaxpr(lambda a, b: a @ b)(
        jnp.ones((4, 8)), jnp.ones((8, 3)))
    # 2*M*N*K = 2*4*3*8
    assert jaxpr_flop_estimate(jaxpr) == pytest.approx(192.0)
    # elementwise arithmetic: ~1 FLOP/element; data movement: 0
    jaxpr2 = jax.make_jaxpr(lambda x: (x + x).reshape(2, 8))(jnp.ones(16))
    assert jaxpr_flop_estimate(jaxpr2) == pytest.approx(16.0)


def test_bytes_touched_and_roofline_row():
    class Plan:
        arg_bytes = 1000
        const_bytes = 200
        out_bytes = 300

    assert bytes_touched(Plan()) == 1500
    r = RooflineRow(program="p", flops=3.0e9, bytes=1.5e7, time_s=0.01,
                    peak_flops=1.0e12, n_devices=2, source="measured")
    assert r.intensity == pytest.approx(200.0)
    assert r.achieved_flops == pytest.approx(3.0e11)
    assert r.mfu == pytest.approx(0.15)
    assert r.ridge_bound == "compute"
    low = RooflineRow(program="q", flops=1.0e6, bytes=1.0e6,
                      peak_flops=1.0e12)
    assert low.ridge_bound == "memory" and low.mfu == 0.0
    unknown = RooflineRow(program="u", flops=1.0, bytes=1.0)
    assert unknown.ridge_bound == ""
    table = format_roofline_table([r, low, unknown])
    assert "p" in table and "n/a" in table
    assert r.as_dict()["mfu"] == pytest.approx(0.15)


def test_rows_from_records_mixed_round_no_keyerror():
    recs = [
        # a bench-stamped record: FLOPs + measured device time
        StepRecord(kind="batched_calculate", bucket_key="n=64/e=256/B=1",
                   timings={"device_s": 0.01}, est_peak_bytes=10**6,
                   num_partitions=2,
                   extra={"flops_per_step": 2.0e9}),
        # warm sibling without the extra — must not erase the group's flops
        StepRecord(kind="batched_calculate", bucket_key="n=64/e=256/B=1",
                   timings={"device_s": 0.02}),
        # compile step: excluded from the warm-step median
        StepRecord(kind="batched_calculate", bucket_key="n=64/e=256/B=1",
                   timings={"device_s": 9.0}, compiled=True),
        # plain serving record with no FLOP estimate: yields no row
        StepRecord(kind="serve_batch", timings={"device_s": 0.005}),
        # old-writer record parsed from JSONL (no compile fields at all)
        StepRecord.from_dict({"kind": "calculate", "step": 1}),
    ]
    rows = rows_from_records(recs)
    assert len(rows) == 1
    row = rows[0]
    assert row.program == "batched_calculate[n=64/e=256/B=1]"
    assert row.flops == pytest.approx(2.0e9)
    assert row.time_s == pytest.approx(0.02)  # median of the warm steps
    assert row.n_devices == 2 and row.source == "measured"
    assert rows_from_records([]) == []


def test_roofline_cli_time_lookup_is_longest_substring():
    import tools.roofline as rl

    times = {"train_step": 1.0, "train_step[tensornet][2x1]": 2.0}
    assert rl._lookup_time("train_step[tensornet][2x1]", times) == 2.0
    assert rl._lookup_time("train_step[tensornet][1x1]", times) == 1.0
    assert rl._lookup_time("potential[mace][1x1]", times) == 0.0


def test_roofline_cli_jsonl_times(tmp_path):
    path = tmp_path / "run.jsonl"
    recs = [
        StepRecord(kind="batched_calculate", bucket_key="b1",
                   timings={"device_s": 0.02}),
        StepRecord(kind="batched_calculate", bucket_key="b1",
                   timings={"device_s": 0.04}),
        StepRecord(kind="batched_calculate", bucket_key="b1",
                   timings={"device_s": 9.0}, compiled=True),
    ]
    path.write_text("".join(r.to_json() + "\n" for r in recs))
    import tools.roofline as rl

    times = rl._times_from_jsonl(str(path))
    assert times["b1"] == pytest.approx(0.04)  # warm median, compile skipped


# ---------------------------------------------------------------------------
# perf-regression baseline gate
# ---------------------------------------------------------------------------


@pytest.fixture()
def pg():
    import tools.perf_gate as pg

    return pg


def test_validate_baseline_schema(pg):
    good = {"schema": 1, "metrics": {
        "v": {"value": 1.0, "tolerance_frac": 0.5,
              "direction": "higher_is_better"}},
        "allow_regressions": []}
    assert pg.validate_baseline(good) == []
    assert pg.validate_baseline([]) != []
    assert pg.validate_baseline({"schema": 99, "metrics": {}}) != []
    bad_dir = {"schema": 1, "metrics": {
        "v": {"value": 1.0, "tolerance_frac": 0.5, "direction": "up"}}}
    assert any("direction" in e for e in pg.validate_baseline(bad_dir))
    bad_allow = {"schema": 1, "metrics": {
        "v": {"value": 1.0, "tolerance_frac": 0.5,
              "direction": "higher_is_better"}},
        "allow_regressions": ["ghost"]}
    assert any("ghost" in e for e in pg.validate_baseline(bad_allow))


def test_compare_statuses_and_allow_list(pg):
    base = {"schema": 1, "allow_regressions": ["lat"], "metrics": {
        "thr": {"value": 100.0, "tolerance_frac": 0.1,
                "direction": "higher_is_better"},
        "lat": {"value": 1.0, "tolerance_frac": 0.1,
                "direction": "lower_is_better"},
        "cnt": {"value": 3.0, "tolerance_frac": 0.0,
                "direction": "lower_is_better"}}}
    by = {n: s for n, s, _ in pg.compare(
        base, {"thr": 50.0, "lat": 2.0, "cnt": 3.0})}
    assert by == {"thr": "regression", "lat": "allowed_regression",
                  "cnt": "ok"}
    by = {n: s for n, s, _ in pg.compare(base, {"thr": 200.0, "cnt": 2.0})}
    assert by["thr"] == "improved" and by["cnt"] == "improved"
    assert by["lat"] == "missing"
    # within-band noise is ok in both directions
    by = {n: s for n, s, _ in pg.compare(
        base, {"thr": 95.0, "lat": 1.05, "cnt": 3.0})}
    assert set(by.values()) == {"ok"}


def test_hbm_drift_watch_runs_whenever_measured(pg):
    assert pg.hbm_drift_findings({}) == []
    flagged = pg.hbm_drift_findings({"hbm_est_over_measured": 5.0})
    assert flagged and flagged[0][1] == "regression"
    ok = pg.hbm_drift_findings({"hbm_estimator_ratio": 1.2})
    assert ok and ok[0][1] == "ok"


def test_perf_gate_cli_exit_codes(pg, tmp_path):
    result = tmp_path / "round.json"
    result.write_text("# noise line\n" + json.dumps(
        {"value": 100.0, "batched_compiles": 2, "note": "str ignored",
         "flag": True}) + "\n")
    baseline = tmp_path / "BASELINE.json"
    assert pg.main(["--input", str(result),
                    "--write-baseline", str(baseline)]) == 0
    doc = json.loads(baseline.read_text())
    assert doc["metrics"]["value"]["direction"] == "higher_is_better"
    assert doc["metrics"]["batched_compiles"]["tolerance_frac"] == 0.0
    assert "flag" not in doc["metrics"] and "note" not in doc["metrics"]

    # identity: exit 0
    assert pg.main(["--input", str(result),
                    "--baseline", str(baseline)]) == 0
    # seeded synthetic regression: exit 3
    reg = tmp_path / "regressed.json"
    reg.write_text(json.dumps({"value": 10.0, "batched_compiles": 5}))
    assert pg.main(["--input", str(reg),
                    "--baseline", str(baseline)]) == 3
    # allow-listed: back to exit 0
    doc["allow_regressions"] = ["value", "batched_compiles"]
    baseline.write_text(json.dumps(doc))
    assert pg.main(["--input", str(reg),
                    "--baseline", str(baseline)]) == 0
    # malformed baseline: exit 2
    baseline.write_text("{\"schema\": 1}")
    assert pg.main(["--input", str(result),
                    "--baseline", str(baseline)]) == 2
    # usage error: both/neither input
    assert pg.main(["--baseline", str(baseline)]) == 2


def test_perf_gate_check_schema_self_test(pg, tmp_path):
    good = tmp_path / "B.json"
    good.write_text(json.dumps({
        "schema": 1, "allow_regressions": [], "metrics": {
            "v": {"value": 1.0, "tolerance_frac": 0.5,
                  "direction": "higher_is_better"}}}))
    assert pg.main(["--check-schema", "--baseline", str(good)]) == 0
    assert pg.main(["--check-schema",
                    "--baseline", str(tmp_path / "missing.json")]) == 2
    bad = tmp_path / "bad.json"
    bad.write_text("not json")
    assert pg.main(["--check-schema", "--baseline", str(bad)]) == 2


def test_committed_baseline_passes_schema(pg):
    """The repo-committed PERF_BASELINE.json stays valid (the same check
    contract_check --lint chains via --check-schema)."""
    path = os.path.join(REPO, "PERF_BASELINE.json")
    assert os.path.exists(path)
    with open(path) as f:
        doc = json.load(f)
    assert pg.validate_baseline(doc) == []


def test_metrics_from_jsonl_compile_split(pg, tmp_path):
    path = tmp_path / "run.jsonl"
    recs = [
        StepRecord(kind="batched_calculate", compiled=True,
                   compile_s=0.5, compile_kind="fresh",
                   timings={"device_s": 0.6}),
        StepRecord(kind="batched_calculate", compile_s=0.01,
                   compile_kind="aot", timings={"device_s": 0.02}),
        StepRecord(kind="batched_calculate", timings={"device_s": 0.01}),
    ]
    path.write_text("".join(r.to_json() + "\n" for r in recs))
    m = pg.metrics_from_jsonl(str(path))
    assert m["compiles_fresh"] == 1.0
    assert m["compiles_aot"] == 1.0
    assert m["compile_time_s"] == pytest.approx(0.51)
    assert m["n_records"] == 3.0


def test_contract_check_lint_chains_perf_gate():
    import subprocess

    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "contract_check.py"),
         "--only-lint", "--json"],
        capture_output=True, text=True, timeout=300)
    rep = json.loads(out.stdout)
    gate = rep["lint"].get("perf_gate")
    assert gate is not None and gate["returncode"] == 0, gate
