"""Telemetry subsystem: StepRecord round-trips, sinks receiving records from
a real DistPotential step (CPU), report aggregation over a synthetic run,
and the zero-overhead disabled path."""

import json

import numpy as np
import pytest

from distmlip_tpu import geometry
from distmlip_tpu.calculators import Atoms, DistPotential
from distmlip_tpu.calculators.device_md import DeviceMD
from distmlip_tpu.models import PairConfig, PairPotential
from distmlip_tpu.telemetry import (AggregatingSink, JsonlSink, StepRecord,
                                    StderrSummarySink, Telemetry, annotate,
                                    set_tracing, tracing_enabled)
from distmlip_tpu.telemetry.report import aggregate, main as report_main, \
    read_jsonl
from distmlip_tpu.telemetry.trace import _NullContext


def make_atoms(rng, reps=(3, 3, 3), a=3.8, noise=0.03):
    unit = np.array([[0, 0, 0], [0.5, 0.5, 0], [0.5, 0, 0.5], [0, 0.5, 0.5]])
    frac, lattice = geometry.make_supercell(unit, np.eye(3) * a, reps)
    cart = geometry.frac_to_cart(frac, lattice) + rng.normal(
        0, noise, (len(frac), 3))
    return Atoms(numbers=np.full(len(cart), 14), positions=cart, cell=lattice)


def _pot(**kw):
    model = PairPotential(PairConfig(cutoff=3.5, kind="lj"))
    params = model.init()
    params = {"eps": params["eps"] * 0.1, "sigma": params["sigma"]}
    return DistPotential(model, params, compute_stress=True, **kw)


# ---------------------------------------------------------------------------
# StepRecord schema
# ---------------------------------------------------------------------------


def test_step_record_roundtrip():
    rec = StepRecord(
        step=7, kind="md_chunk",
        timings={"neighbor_s": 0.01, "partition_s": 0.002, "device_s": 0.1,
                 "total_s": 0.115},
        n_atoms=108, num_partitions=2, n_cap=128, e_cap=2048,
        n_nodes_per_part=[64, 60], n_edges_per_part=[1500, 1400],
        node_occupancy=0.5, edge_occupancy=0.73,
        halo_send_per_part=[12, 10], halo_recv_per_part=[10, 12],
        graph_reused=True, compiled=True, compile_cache_size=3,
        device_memory={"dev0_bytes_in_use": 1 << 20},
        extra={"steps_done": 40},
    )
    back = StepRecord.from_json(rec.to_json())
    assert back == rec
    # JSONL line is a flat JSON object
    d = json.loads(rec.to_json())
    assert d["kind"] == "md_chunk" and d["extra"]["steps_done"] == 40


def test_step_record_forward_compat():
    """Unknown keys from a newer writer land in extra, not lost/crashing."""
    d = StepRecord(step=1).to_dict()
    d["future_field"] = 42
    back = StepRecord.from_dict(d)
    assert back.step == 1 and back.extra["future_field"] == 42


def test_step_record_total_and_imbalance():
    r = StepRecord(timings={"neighbor_s": 0.2, "device_s": 0.3})
    assert r.total_s == pytest.approx(0.5)
    r2 = StepRecord(halo_send_per_part=[30, 10])
    assert r2.halo_imbalance() == pytest.approx(1.5)
    assert StepRecord().halo_imbalance() == 1.0


# ---------------------------------------------------------------------------
# sinks receiving records from a real CPU DistPotential step
# ---------------------------------------------------------------------------


def test_distpotential_emits_records(rng, tmp_path):
    path = str(tmp_path / "run.jsonl")
    agg = AggregatingSink()
    tel = Telemetry([agg, JsonlSink(path)])
    pot = _pot(num_partitions=2, telemetry=tel)
    atoms = make_atoms(rng)
    for _ in range(3):
        atoms.positions += rng.normal(0, 0.01, atoms.positions.shape)
        pot.calculate(atoms)
    tel.close()

    assert agg.n_records == 3
    assert agg.totals["device_s"] > 0
    recs = read_jsonl(path)
    assert len(recs) == 3
    for r in recs:
        assert r.kind == "calculate"
        assert r.num_partitions == 2 and r.n_atoms == len(atoms)
        # per-phase timings present
        for k in ("neighbor_s", "partition_s", "device_s", "total_s"):
            assert k in r.timings
        # graph shape + padding occupancy
        assert r.n_cap > 0 and 0 < r.node_occupancy <= 1.0
        assert r.e_cap > 0 and 0 < r.edge_occupancy <= 1.0
        assert len(r.n_nodes_per_part) == 2
        # halo volumes per partition (P=2 slabs exchange both directions)
        assert len(r.halo_send_per_part) == 2
        assert all(v > 0 for v in r.halo_send_per_part)
        # every sent row is received somewhere
        assert sum(r.halo_recv_per_part) == sum(r.halo_send_per_part)
        # skin=0: every step rebuilds
        assert r.rebuild and not r.graph_reused
    # first step compiled the potential, later steps hit the executable cache
    assert recs[0].compiled
    assert recs[0].compile_cache_size >= 1
    assert not recs[-1].compiled
    # summary renders the phase table
    s = agg.summary()
    assert "device_s" in s and "records=3" in s


def test_skin_cache_hits_recorded(rng):
    agg = AggregatingSink()
    pot = _pot(num_partitions=1, skin=1.0, async_rebuild=False,
               telemetry=Telemetry([agg]))
    atoms = make_atoms(rng)
    pot.calculate(atoms)
    atoms.positions += 1e-4  # far inside the Verlet budget
    pot.calculate(atoms)
    assert agg.rebuilds == 1
    assert agg.n_records == 2


def test_device_md_chunk_records(rng):
    agg = AggregatingSink()
    pot = _pot(num_partitions=1, skin=1.0, async_rebuild=False)
    atoms = make_atoms(rng)
    atoms.set_maxwell_boltzmann_velocities(50.0, rng=rng)
    md = DeviceMD(pot, atoms, timestep=0.5, telemetry=Telemetry([agg]))
    md.run(10)
    assert agg.n_records >= 1
    assert agg.totals["device_s"] > 0
    assert agg.samples["total_s"]  # chunk wall time recorded


def test_aggregating_sink_bounded_memory():
    """Sample buffers decimate past max_samples; totals/means stay exact."""
    agg = AggregatingSink(max_samples=64)
    n = 1000
    for i in range(n):
        agg.emit(StepRecord(timings={"device_s": float(i)}))
    assert len(agg.samples["device_s"]) < 64
    s = agg.phase_stats("device_s")
    assert s["count"] == n
    assert s["total_s"] == pytest.approx(sum(range(n)))
    assert s["mean_s"] == pytest.approx(sum(range(n)) / n)
    # decimated percentiles still track the distribution
    assert abs(s["p50_s"] - n / 2) < n * 0.05
    # no halo data -> no imbalance stat claimed (matches report.py)
    assert agg.max_halo_imbalance == 0.0
    assert "max_halo_imbalance" not in agg.summary()


def test_emit_after_close_is_noop(tmp_path):
    path = str(tmp_path / "x.jsonl")
    tel = Telemetry([JsonlSink(path), AggregatingSink()])
    tel.emit(StepRecord(step=0, timings={"total_s": 0.1}))
    tel.close()
    tel.emit(StepRecord(step=1, timings={"total_s": 0.1}))  # must not raise
    assert len(read_jsonl(path)) == 1


def test_stderr_summary_sink(capsys):
    sink = StderrSummarySink(every=2)
    tel = Telemetry([sink])
    for i in range(3):
        tel.emit(StepRecord(step=i, timings={"device_s": 0.01},
                            node_occupancy=0.8, rebuild=(i == 0)))
    tel.close()
    err = capsys.readouterr().err
    # one periodic line (step 1) + one close line (step 2)
    assert err.count("# telemetry") == 2
    assert "node_occ=0.80" in err


# ---------------------------------------------------------------------------
# report aggregation
# ---------------------------------------------------------------------------


def _synthetic_run(path, n=20):
    with open(path, "w") as f:
        for i in range(n):
            rec = StepRecord(
                step=i, timings={"neighbor_s": 0.01, "device_s": 0.10,
                                 "total_s": 0.11},
                n_atoms=256, num_partitions=4, n_cap=128, e_cap=1024,
                node_occupancy=0.8, edge_occupancy=0.75,
                halo_send_per_part=[10, 11, 10, 9],
                rebuild=(i % 5 == 0), graph_reused=(i % 5 != 0))
            f.write(rec.to_json() + "\n")
        # wedge-style stall
        f.write(StepRecord(step=n, timings={"device_s": 5.0, "total_s": 5.0},
                           node_occupancy=0.8, edge_occupancy=0.7,
                           ).to_json() + "\n")
        # occupancy collapse + halo imbalance
        f.write(StepRecord(step=n + 1,
                           timings={"device_s": 0.1, "total_s": 0.11},
                           node_occupancy=0.1, edge_occupancy=0.08,
                           halo_send_per_part=[100, 5, 5, 5],
                           ).to_json() + "\n")


def test_report_aggregates_and_flags(tmp_path):
    path = str(tmp_path / "synthetic.jsonl")
    _synthetic_run(path)
    recs = read_jsonl(path)
    rep = aggregate(recs)
    assert rep.n_records == 22
    assert rep.phases["device_s"]["count"] == 22
    assert rep.phases["device_s"]["max_s"] == pytest.approx(5.0)
    assert rep.phases["neighbor_s"]["p50_s"] == pytest.approx(0.01)
    kinds = {a.kind for a in rep.anomalies}
    assert kinds == {"stall", "occupancy_collapse", "halo_imbalance"}
    txt = rep.render()
    assert "ANOMALIES" in txt and "device_s" in txt
    # per-phase table has the percentile columns
    assert "p99_ms" in rep.table()


def test_stall_detection_is_per_kind():
    """A DeviceMD chunk legitimately spans many calculate-steps of wall
    time; it must not be flagged as a stall against the calculate median."""
    recs = [StepRecord(step=i, kind="calculate",
                       timings={"total_s": 0.1}) for i in range(10)]
    recs += [StepRecord(step=10 + i, kind="md_chunk",
                        timings={"total_s": 5.0}) for i in range(4)]
    rep = aggregate(recs)
    assert not [a for a in rep.anomalies if a.kind == "stall"]
    # a genuine stall WITHIN a kind still flags
    recs.append(StepRecord(step=99, kind="md_chunk",
                           timings={"total_s": 100.0}))
    rep = aggregate(recs)
    stalls = [a for a in rep.anomalies if a.kind == "stall"]
    assert len(stalls) == 1 and stalls[0].step == 99


def test_report_cli(tmp_path, capsys):
    path = str(tmp_path / "synthetic.jsonl")
    _synthetic_run(path)
    out_json = str(tmp_path / "report.json")
    rc = report_main([path, "--json", out_json])
    assert rc == 4  # anomalies flagged
    out = capsys.readouterr().out
    assert "phase" in out and "ANOMALIES" in out
    rep = json.loads(open(out_json).read())
    assert rep["n_records"] == 22 and rep["anomalies"]
    # clean run exits 0
    clean = str(tmp_path / "clean.jsonl")
    with open(clean, "w") as f:
        for i in range(5):
            f.write(StepRecord(step=i, timings={"device_s": 0.1,
                                                "total_s": 0.1},
                               node_occupancy=0.9,
                               edge_occupancy=0.9).to_json() + "\n")
    assert report_main([clean]) == 0
    assert report_main([]) == 2  # usage


def test_report_skips_corrupt_lines(tmp_path):
    path = str(tmp_path / "trunc.jsonl")
    with open(path, "w") as f:
        f.write(StepRecord(step=0, timings={"total_s": 0.1}).to_json() + "\n")
        f.write('{"step": 1, "timings"')  # killed mid-write
    assert len(read_jsonl(path)) == 1


def test_report_mixed_compile_telemetry_records():
    """A round mixing writers — some records carry the PR-16 compile
    fields, some are old-writer JSONL without them — must aggregate and
    render without KeyErrors, with the compile split counting only the
    records that have it and percentiles unskewed by the absent fields."""
    recs = [
        StepRecord(step=0, compiled=True, compile_s=0.8,
                   compile_kind="fresh",
                   timings={"device_s": 0.9, "total_s": 0.95}),
        StepRecord(step=1, compile_s=0.01, compile_kind="aot",
                   timings={"device_s": 0.02, "total_s": 0.03}),
    ]
    # old-writer records: parsed from dicts WITHOUT the compile fields
    recs += [StepRecord.from_dict(
        {"step": 2 + i, "timings": {"device_s": 0.1, "total_s": 0.11}})
        for i in range(8)]
    rep = aggregate(recs)
    assert rep.counters["compiles_fresh"] == 1
    assert rep.counters["compiles_aot"] == 1
    assert rep.counters["compile_time_s"] == pytest.approx(0.81)
    # the old-writer majority keeps the warm percentile honest
    assert rep.phases["device_s"]["p50_s"] == pytest.approx(0.1)
    txt = rep.render()
    assert "compile: fresh=1 aot_rehydrate=1" in txt


def test_report_no_compile_fields_at_all():
    """Pure old-writer rounds carry NO compile keys — the report omits
    the section instead of inventing zeros."""
    recs = [StepRecord.from_dict(
        {"step": i, "timings": {"total_s": 0.1}}) for i in range(5)]
    rep = aggregate(recs)
    assert "compiles_fresh" not in rep.counters
    assert "compile:" not in rep.render()


def test_report_roofline_section_from_records():
    """Records carrying FLOP estimates surface a roofline table in the
    report; mixed groups without estimates degrade to fewer rows."""
    recs = [
        StepRecord(step=0, kind="batched_calculate", bucket_key="b1",
                   timings={"device_s": 0.01, "total_s": 0.02},
                   est_peak_bytes=10**6,
                   extra={"flops_per_step": 1.0e9}),
        StepRecord(step=1, kind="serve_batch",
                   timings={"device_s": 0.005, "total_s": 0.01}),
    ]
    rep = aggregate(recs)
    rows = rep.counters.get("roofline", [])
    assert len(rows) == 1
    assert rows[0]["program"] == "batched_calculate[b1]"
    assert "roofline" in rep.render()


# ---------------------------------------------------------------------------
# disabled path: zero overhead
# ---------------------------------------------------------------------------


def test_annotate_noop_when_disabled():
    assert not tracing_enabled()
    cm = annotate("distmlip/neighbor_build")
    assert isinstance(cm, _NullContext)
    # the SAME shared object every call — no per-call allocation
    assert annotate("other") is cm
    with cm:
        pass
    set_tracing(True)
    try:
        assert not isinstance(annotate("x"), _NullContext)
    finally:
        set_tracing(False)


def test_no_records_without_telemetry(rng, monkeypatch):
    """With telemetry unset, calculate() never constructs a StepRecord."""
    import distmlip_tpu.calculators.calculator as calc_mod

    def boom(*a, **kw):
        raise AssertionError("StepRecord constructed on the disabled path")

    monkeypatch.setattr(calc_mod, "StepRecord", boom)
    pot = _pot(num_partitions=1)
    res = pot.calculate(make_atoms(rng))
    assert np.isfinite(res["energy"])
    # last_timings backward-compat surface still populated
    assert pot.last_timings["device_s"] > 0


def test_disabled_hub_not_invoked(rng):
    class Exploding(AggregatingSink):
        def emit(self, record):
            raise AssertionError("sink invoked while disabled")

    tel = Telemetry([Exploding()], enabled=False)
    pot = _pot(num_partitions=1, telemetry=tel)
    res = pot.calculate(make_atoms(rng))
    assert np.isfinite(res["energy"])
