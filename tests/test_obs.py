"""Observability: tracing, span conservation under failover, metrics,
SLO burn rates, flight recorder, JSONL rotation, DML003 lint.

The contracts under test:

- every submitted request leaves a CLOSED span tree ending in exactly
  one ``future.resolve`` terminal — through cache hits, coalesced
  duplicates, and a mid-burst ``kill_replica()`` (the failover trace-
  propagation satellite);
- batch-dispatch spans carry links to every member request, and the
  per-request critical path (interval union) explains >= 90% of the
  measured request latency;
- the metrics registry's Prometheus exposition round-trips the counters
  the loadgen can verify; the SLO monitor fires on multi-window burn
  and respects cooldown; the flight recorder writes bounded incidents;
- ``JsonlSink`` rotation keeps the artifact set bounded without losing
  or splitting records;
- span creation inside a jitted function is lint rule DML003.
"""

import json
import os
import threading
import urllib.request

import numpy as np
import pytest

from distmlip_tpu import geometry, obs
from distmlip_tpu.calculators import Atoms, BatchedPotential
from distmlip_tpu.fleet import FleetRouter, ResultCache
from distmlip_tpu.models import PairConfig, PairPotential
from distmlip_tpu.obs import (FlightRecorder, MetricsRegistry, MetricsServer,
                              Observability, SLOConfig, SLOMonitor, Tracer,
                              critical_path_summary, load_trace,
                              parse_exposition, request_trace_summary,
                              uninstall)
from distmlip_tpu.partition import BucketPolicy
from distmlip_tpu.serve import ServeEngine
from distmlip_tpu.telemetry import JsonlSink, StepRecord

pytestmark = pytest.mark.obs


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture(scope="module")
def pair():
    model = PairPotential(PairConfig(cutoff=4.0))
    return model, model.init()


@pytest.fixture
def hub():
    h = Observability.enable()
    try:
        yield h
    finally:
        uninstall()


def make_structure(rng, noise=0.05):
    unit = np.array([[0, 0, 0], [0.5, 0.5, 0], [0.5, 0, 0.5],
                     [0, 0.5, 0.5]])
    frac, lat = geometry.make_supercell(unit, np.eye(3) * 3.6, (2, 2, 2))
    cart = geometry.frac_to_cart(frac, lat) + rng.normal(
        0, noise, (len(frac), 3))
    return Atoms(numbers=np.full(len(cart), 14), positions=cart, cell=lat)


def make_engine(pair, **kw):
    model, params = pair
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_wait_s", 0.005)
    kw.setdefault("max_queue", 4096)
    return ServeEngine(BatchedPotential(model, params, caps=BucketPolicy()),
                       **kw)


# ---------------------------------------------------------------------------
# tracer core
# ---------------------------------------------------------------------------


@pytest.mark.tier1
def test_tracer_nesting_parents_and_new_trace():
    tr = Tracer()
    with tr.span("outer") as outer:
        assert tr.current() == outer.ctx
        with tr.span("inner") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
        with tr.span("island", new_trace=True) as island:
            assert island.trace_id != outer.trace_id
    assert tr.current() is None
    # explicit parent beats ambient; retroactive emit commits closed
    s = tr.emit("retro", parent=outer, t_start=1.0, t_end=2.0)
    assert (s.trace_id, s.parent_id) == (outer.trace_id, outer.span_id)
    assert s.duration_s == 1.0
    names = [x.name for x in tr.spans()]
    assert names == ["inner", "island", "outer", "retro"]  # finish order


@pytest.mark.tier1
def test_tracer_cross_thread_request_handle():
    tr = Tracer()
    rt = tr.start_request("engine.submit")
    seen = {}

    def worker():
        # no ambient context in this thread: the handle IS the context
        assert tr.current() is None
        tr.emit("engine.queue", parent=rt.ctx, t_start=rt.t_submit)
        seen["ok"] = True

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    assert seen["ok"]
    tr.finish_request(rt, "ok")
    s = request_trace_summary(tr.spans())
    assert s["requests"] == s["complete"] == 1
    assert s["terminals"] == 1


@pytest.mark.tier1
def test_tracer_ring_bound_counts_drops():
    tr = Tracer(max_spans=8)
    for i in range(20):
        tr.emit(f"s{i}", new_trace=True)
    assert len(tr.spans()) == 8
    assert tr.spans_dropped == 12
    assert tr.spans_finished == 20


@pytest.mark.tier1
def test_perfetto_roundtrip_preserves_summary(tmp_path):
    tr = Tracer()
    rt = tr.start_request("fleet.submit", attrs={"tenant": "a"})
    with tr.span("serve.batch", new_trace=True, links=[rt.ctx]) as b:
        t0 = tr.now()
        tr.emit("batched.pack", parent=b, t_start=t0, t_end=t0 + 0.01)
        tr.emit("device.dispatch", parent=b, t_start=t0 + 0.01,
                t_end=t0 + 0.03)
    tr.emit("engine.queue", parent=rt.ctx, t_start=rt.t_submit)
    tr.finish_request(rt, "ok")
    path = tr.write(str(tmp_path / "t.json"))
    spans = load_trace(path)
    s = request_trace_summary(spans)
    assert s["requests"] == s["complete"] == 1
    # links survive the round trip: batch phases attribute to the request
    cs = critical_path_summary(spans)
    assert cs["requests"] == 1
    assert cs["components"]["pack"]["max"] > 0
    # the file is a loadable Chrome trace object
    with open(path) as f:
        obj = json.load(f)
    assert any(ev.get("ph") == "X" for ev in obj["traceEvents"])


@pytest.mark.tier1
def test_critical_path_queue_dominant_flag():
    tr = Tracer(clock=FakeClock())
    clock = tr._clock
    for _ in range(4):
        rt = tr.start_request("engine.submit")
        clock.advance(1.0)            # 1 s queue wait
        tr.emit("engine.queue", parent=rt.ctx, t_start=rt.t_submit)
        with tr.span("serve.batch", new_trace=True, links=[rt.ctx]) as b:
            t0 = tr.now()
            clock.advance(0.01)       # 10 ms device
            tr.emit("device.dispatch", parent=b, t_start=t0)
        tr.finish_request(rt, "ok")
    cs = critical_path_summary(tr.spans())
    assert cs["queue_dominant"]
    assert cs["components"]["queue"]["p50"] == pytest.approx(1.0, rel=0.01)
    assert cs["coverage_p50"] > 0.95


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------


@pytest.mark.tier1
def test_metrics_registry_counters_gauges_histograms():
    m = MetricsRegistry()
    c = m.counter("reqs_total", "requests", labels=("tenant",))
    c.labels(tenant="a").inc()
    c.labels(tenant="a").inc(2)
    c.labels(tenant="b").inc()
    m.gauge("depth", "queue depth").set(7)
    h = m.histogram("lat_seconds", "latency")
    for v in (0.0002, 0.0002, 0.1):
        h.observe(v)
    vals = parse_exposition(m.render())
    assert vals['reqs_total{tenant="a"}'] == 3.0
    assert vals['reqs_total{tenant="b"}'] == 1.0
    assert vals["depth"] == 7.0
    assert vals["lat_seconds_count"] == 3.0
    assert vals["lat_seconds_sum"] == pytest.approx(0.1004)
    # log-bucket quantile: upper bound of the bucket the rank falls in
    assert h.quantile(0.5) == pytest.approx(0.0002)
    assert h.quantile(0.99) >= 0.1
    # snapshot is JSON-dumpable (the bench artifact path)
    json.dumps(m.snapshot())
    # re-registration: same kind returns the family, new kind raises
    assert m.counter("reqs_total", labels=("tenant",)) is c
    with pytest.raises(ValueError):
        m.gauge("reqs_total")


@pytest.mark.tier1
def test_metrics_server_scrapes():
    m = MetricsRegistry()
    m.counter("up_total", "x").inc(5)
    with MetricsServer(m, port=0) as srv:
        assert srv.port > 0
        body = urllib.request.urlopen(srv.url, timeout=10).read().decode()
    assert parse_exposition(body)["up_total"] == 5.0


# ---------------------------------------------------------------------------
# SLO monitor + flight recorder
# ---------------------------------------------------------------------------


@pytest.mark.tier1
def test_slo_burn_rate_breach_fires_once_per_cooldown():
    clock = FakeClock()
    fired = []
    mon = SLOMonitor(default=SLOConfig(
        latency_s=0.1, objective=0.9, fast_window_s=10.0,
        slow_window_s=60.0, fast_burn=5.0, slow_burn=3.0,
        min_requests=8, cooldown_s=30.0), clock=clock,
        on_breach=lambda t, info: fired.append(info))
    # healthy traffic: no breach
    for _ in range(20):
        clock.advance(0.5)
        mon.observe("a", 0.01)
    assert not fired
    # sustained badness: exactly ONE firing inside the cooldown window
    for _ in range(20):
        clock.advance(0.5)
        mon.observe("a", 1.0)
    assert len(fired) == 1
    assert fired[0]["tenant"] == "a"
    assert fired[0]["fast_burn"] >= 5.0
    clock.advance(31.0)              # past cooldown: it may fire again
    for _ in range(10):
        clock.advance(0.2)
        mon.observe("a", 1.0)
    assert len(fired) == 2
    snap = mon.snapshot()
    assert snap["a"]["breaches"] == 2 and snap["a"]["bad"] == 30


@pytest.mark.tier1
def test_slo_min_requests_guards_tiny_samples():
    clock = FakeClock()
    fired = []
    mon = SLOMonitor(default=SLOConfig(
        latency_s=0.1, min_requests=50, fast_window_s=10,
        slow_window_s=60), clock=clock,
        on_breach=lambda t, info: fired.append(info))
    for _ in range(20):
        clock.advance(0.1)
        mon.observe("a", 9.9)
    assert not fired                 # 100% bad, but n < min_requests


@pytest.mark.tier1
def test_flight_recorder_capture_and_rate_limit(tmp_path):
    clock = FakeClock()
    tr = Tracer()
    rt = tr.start_request("engine.submit")
    tr.finish_request(rt, "ok")
    m = MetricsRegistry()
    m.counter("c_total", "x").inc()
    fr = FlightRecorder(str(tmp_path), tracer=tr, metrics=m,
                        min_interval_s=10.0, clock=clock)
    d = fr.capture("test", attrs={"k": 1})
    assert d is not None and os.path.isdir(d)
    names = sorted(os.listdir(d))
    assert names == ["incident.json", "metrics.json", "metrics.prom",
                     "trace.json"]
    with open(os.path.join(d, "incident.json")) as f:
        meta = json.load(f)
    assert meta["reason"] == "test" and meta["attrs"] == {"k": 1}
    # the captured trace is loadable and complete
    s = request_trace_summary(load_trace(os.path.join(d, "trace.json")))
    assert s["complete"] == 1
    assert "c_total 1" in open(os.path.join(d, "metrics.prom")).read()
    # rate limit: suppressed inside the interval, allowed after
    assert fr.capture("again") is None
    assert fr.suppressed == 1
    clock.advance(11.0)
    assert fr.capture("later") is not None
    assert fr.snapshot()["captures"] == 2


def test_slo_breach_autocaptures_through_hub(tmp_path):
    clock = FakeClock()
    h = Observability.enable(
        slo=SLOConfig(latency_s=0.1, min_requests=4, fast_window_s=10,
                      slow_window_s=60, fast_burn=2.0, slow_burn=2.0),
        flight_dir=str(tmp_path), min_interval_s=0.0, clock=clock,
        register=False)
    for _ in range(10):
        clock.advance(0.2)
        h.slo.observe("t", 5.0)
    assert h.flight.captures >= 1
    inc = h.flight.incidents[0]
    meta = json.load(open(os.path.join(inc, "incident.json")))
    assert "burn-rate breach" in meta["reason"]


# ---------------------------------------------------------------------------
# JsonlSink rotation (satellite)
# ---------------------------------------------------------------------------


@pytest.mark.tier1
def test_jsonl_sink_rotation_bounds_and_preserves_records(tmp_path):
    path = str(tmp_path / "run.jsonl")
    sink = JsonlSink(path, max_bytes=2048, keep=2)
    n = 100
    for i in range(n):
        sink.emit(StepRecord(step=i, kind="t"))
    stats = sink.stats()
    sink.close()
    assert stats["rotations"] >= 2
    assert stats["lines"] == n
    # at most keep rotated files + the live one, each bounded
    rotated = sink.rotated_paths()
    assert 1 <= len(rotated) <= 2
    for p in (path, *rotated):
        assert os.path.getsize(p) <= 2048 + 512   # one record of slack
    # rotation never loses or splits a record: every surviving line
    # parses, steps are contiguous across the file seams (newest last),
    # and the newest surviving record is the last one emitted
    from distmlip_tpu.telemetry.report import read_jsonl

    steps = []
    for p in (*reversed(rotated), path):   # oldest -> newest
        steps.extend(r.step for r in read_jsonl(p))
    assert steps
    assert steps == list(range(steps[0], n))   # contiguous, none split
    assert steps[-1] == n - 1


@pytest.mark.tier1
def test_jsonl_sink_no_rotation_by_default(tmp_path):
    path = str(tmp_path / "run.jsonl")
    sink = JsonlSink(path)
    for i in range(50):
        sink.emit(StepRecord(step=i))
    sink.close()
    assert sink.stats()["rotations"] == 0
    assert sink.rotated_paths() == []
    with pytest.raises(ValueError):
        JsonlSink(str(tmp_path / "x.jsonl"), max_bytes=0)


# ---------------------------------------------------------------------------
# DML003 lint (satellite)
# ---------------------------------------------------------------------------


@pytest.mark.tier1
@pytest.mark.contracts
def test_lint_dml003_flags_span_in_jit(tmp_path):
    from distmlip_tpu.analysis.lint import lint_file

    src = '''
import jax
from distmlip_tpu.obs import runtime as obsrt

@jax.jit
def bad_step(x):
    tr = obsrt.tracer()
    with tr.span("device.math"):      # DML003: host span in jit
        return x * 2

def good_host(x):
    tr = obsrt.tracer()
    with tr.span("host.phase"):       # host fn: fine
        return x * 2

def energy_fn(params, lg, positions):
    from distmlip_tpu.telemetry import scope
    with scope("model/forward"):      # named_scope is exempt
        return positions.sum()
'''
    p = tmp_path / "seeded.py"
    p.write_text(src)
    findings = [f for f in lint_file(str(p)) if not f.suppressed]
    dml3 = [f for f in findings if f.rule == "DML003"]
    assert len(dml3) == 1
    assert dml3[0].location[1] == src.splitlines().index(
        '    with tr.span("device.math"):      # DML003: host span in jit'
    ) + 1
    # suppression comment works like every other rule
    src2 = src.replace(
        'with tr.span("device.math"):      # DML003: host span in jit',
        'with tr.span("device.math"):  # contract: allow(DML003)')
    p2 = tmp_path / "suppressed.py"
    p2.write_text(src2)
    assert not [f for f in lint_file(str(p2))
                if f.rule == "DML003" and not f.suppressed]


@pytest.mark.tier1
@pytest.mark.contracts
def test_lint_dml003_clean_on_repo():
    """The shipped instrumentation never creates spans in device code."""
    from distmlip_tpu.analysis.lint import lint_paths

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings = lint_paths([os.path.join(repo, "distmlip_tpu")],
                          package_root=repo)
    assert not [f for f in findings
                if f.rule == "DML003" and not f.suppressed]


# ---------------------------------------------------------------------------
# engine + fleet integration: span conservation
# ---------------------------------------------------------------------------


@pytest.mark.tier1
def test_engine_traces_complete_and_records_stamped(rng, pair, hub):
    from distmlip_tpu.telemetry import Telemetry

    class _ListSink:
        def __init__(self):
            self.records = []

        def emit(self, r):
            self.records.append(r)

        def close(self):
            pass

    sink = _ListSink()
    engine = make_engine(pair, telemetry=Telemetry([sink]))
    futs = [engine.submit(make_structure(rng)) for _ in range(10)]
    for f in futs:
        assert "energy" in f.result(timeout=120)
    engine.drain(timeout=60)
    engine.close()
    spans = hub.tracer.spans()
    s = request_trace_summary(spans)
    assert s["requests"] == s["complete"] == 10
    assert s["terminals"] == 10
    # batch spans link member requests and phases attribute to them
    cs = critical_path_summary(spans)
    assert cs["coverage_p50"] >= 0.9
    # serve_batch records carry the batch span ids; batched_calculate
    # records stamp the ambient context — both correlate with the trace
    batch_recs = [r for r in sink.records if r.kind == "serve_batch"]
    assert batch_recs and all(r.trace_id for r in batch_recs)
    trace_ids = {sp.trace_id for sp in spans}
    assert all(r.trace_id in trace_ids for r in batch_recs)
    pot_recs = [r for r in sink.records if r.kind == "batched_calculate"]
    assert pot_recs and all(r.trace_id for r in pot_recs)
    # live metrics populated from the same instrumentation points
    vals = parse_exposition(hub.metrics.render())
    assert vals["distmlip_serve_submitted_total"] == 10.0
    assert vals["distmlip_serve_completed_total"] == 10.0


@pytest.mark.tier1
def test_engine_error_paths_close_traces(rng, pair, hub):
    engine = make_engine(pair)
    good = engine.submit(make_structure(rng))
    bad_atoms = make_structure(rng)
    bad_atoms.positions = bad_atoms.positions.copy()
    bad_atoms.positions[0, 0] = np.nan
    bad = engine.submit(bad_atoms)
    assert "energy" in good.result(timeout=120)
    with pytest.raises(Exception):
        bad.result(timeout=120)
    engine.close()
    s = request_trace_summary(hub.tracer.spans())
    # the poison request still leaves a complete tree (terminal: error)
    assert s["requests"] == s["complete"] == 2
    assert s["terminals"] == 2


def test_failover_trace_propagation_kill_replica_mid_burst(rng, pair, hub):
    """The satellite contract: kill_replica() mid-burst must leave every
    reclaimed request with a complete span tree ending in exactly one
    future.resolve — no orphan or duplicate terminal spans — and
    span-count conservation must hold across the cache-hit and coalesce
    short-circuits in the same run."""
    router = FleetRouter([make_engine(pair) for _ in range(2)],
                         result_cache=ResultCache(), model_id="pair")
    structs = [make_structure(rng) for _ in range(30)]
    futs = [router.submit(a) for a in structs[:15]]
    moved = router.kill_replica("r0")
    futs += [router.submit(a) for a in structs[15:]]
    for f in futs:
        assert "energy" in f.result(timeout=120)
    router.drain(timeout=60)
    # cache hits + a coalesce race: each submission still owns a tree
    dup_futs = [router.submit(structs[0]) for _ in range(3)]
    fresh = make_structure(rng)
    co1, co2 = router.submit(fresh), router.submit(fresh)
    for f in (*dup_futs, co1, co2):
        assert "energy" in f.result(timeout=120)
    router.drain(timeout=60)
    router.close()
    assert moved >= 1
    assert router.stats.failovers == 1 and router.stats.failed == 0
    n_submitted = len(futs) + len(dup_futs) + 2
    s = request_trace_summary(hub.tracer.spans())
    assert s["requests"] == n_submitted
    assert s["complete"] == n_submitted          # every tree closed
    assert s["terminals"] == n_submitted         # exactly one each
    assert s["terminal_violation_count"] == 0    # no orphan/duplicate
    assert hub.tracer.spans_dropped == 0
    # re-dispatched requests carry their failover history as spans
    requeues = [sp for sp in hub.tracer.spans()
                if sp.name == "router.requeue"]
    assert len(requeues) >= moved
    # and the critical path still explains the measured latency
    cs = critical_path_summary(hub.tracer.spans())
    assert cs["coverage_p50"] >= 0.9
    # failover metrics moved with it
    vals = parse_exposition(hub.metrics.render())
    assert vals["distmlip_fleet_failovers_total"] == 1.0
    assert vals['distmlip_replica_alive{replica="r0"}'] == 0.0
    assert vals['distmlip_replica_alive{replica="r1"}'] == 1.0


def test_report_trace_dir_renders_critical_path(tmp_path, rng, pair, hub,
                                                capsys):
    """telemetry_report --trace-dir: per-request percentiles next to the
    per-phase table, queue_dominant flagged as an anomaly (exit 4)."""
    from distmlip_tpu.telemetry import JsonlSink, Telemetry
    from distmlip_tpu.telemetry.report import main as report_main

    jsonl = str(tmp_path / "run.jsonl")
    tel = Telemetry([JsonlSink(jsonl)])
    # force queue dominance: a tiny max_batch + burst of submissions
    engine = make_engine(pair, max_batch=1, max_wait_s=0.0,
                         telemetry=tel)
    futs = [engine.submit(make_structure(rng)) for _ in range(8)]
    for f in futs:
        f.result(timeout=120)
    engine.close()
    tel.close()
    tdir = tmp_path / "traces"
    tdir.mkdir()
    hub.tracer.write(str(tdir / "burst.json"))
    rc = report_main([jsonl, "--trace-dir", str(tdir)])
    out = capsys.readouterr().out
    assert "trace critical path (8 request(s)):" in out
    assert "queue" in out and "device" in out.lower()
    if "queue_dominant=True" in out:
        assert rc == 4
        assert "[queue_dominant]" in out
    else:                             # machine too fast to queue: still ok
        assert rc in (0, 4)


def test_load_test_cli_metrics_and_trace_gates(tmp_path):
    """tools/load_test.py --fleet --check with --metrics-port and
    --trace-out: the trace_complete + metrics_scrape gates hold and the
    exported trace is a valid Perfetto artifact."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    trace_out = str(tmp_path / "trace.json")
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "load_test.py"),
         "--fleet", "2", "--requests", "16", "--check",
         "--metrics-port", "0", "--trace-out", trace_out],
        cwd=repo, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["check"] == "ok"
    assert summary["checks"]["trace_complete"]
    assert summary["checks"]["trace_critical_path"]
    assert summary["checks"]["metrics_scrape"]
    assert summary["trace"]["terminal_violations"] == 0
    spans = load_trace(trace_out)
    s = request_trace_summary(spans)
    assert s["requests"] == summary["trace"]["request_traces"]
    assert s["complete"] == s["requests"]
