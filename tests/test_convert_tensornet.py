"""TensorNet weight conversion: matgl-shaped torch state dicts -> our params.

Torch mirror of matgl's TensorNet module tree (torchmd-net port; module
inventory from the reference wrapper's enable_distributed_mode, reference
implementations/matgl/models/tensornet.py:179-197, readout math from
dist_forward :131-159) with an explicit-loop float64 oracle forward.
"""

import numpy as np
import pytest
import torch
import torch.nn as nn

import jax

from distmlip_tpu.models.tensornet import TensorNet, TensorNetConfig
from distmlip_tpu.models.convert import from_torch
from tests.test_convert_chgnet import TMLP
from tests.utils import run_potential

# converter goldens are slow-lane: they re-run the torch oracle forward
pytestmark = pytest.mark.slow

S, C, R, NL = 4, 8, 6, 2
CUT = 3.0


class TTensorEmbedding(nn.Module):
    def __init__(self, S, C, R):
        super().__init__()
        self.emb = nn.Embedding(S, C)
        self.emb2 = nn.Linear(2 * C, C)
        self.distance_proj1 = nn.Linear(R, C)
        self.distance_proj2 = nn.Linear(R, C)
        self.distance_proj3 = nn.Linear(R, C)
        self.linears_scalar = nn.ModuleList(
            [nn.Linear(C, 2 * C), nn.Linear(2 * C, 3 * C)])
        self.linears_tensor = nn.ModuleList(
            [nn.Linear(C, C, bias=False) for _ in range(3)])
        self.init_norm = nn.LayerNorm(C)


class TInteraction(nn.Module):
    def __init__(self, C, R):
        super().__init__()
        self.linears_scalar = nn.ModuleList(
            [nn.Linear(R, C), nn.Linear(C, 2 * C), nn.Linear(2 * C, 3 * C)])
        self.linears_tensor = nn.ModuleList(
            [nn.Linear(C, C, bias=False) for _ in range(6)])


class TReadOut(nn.Module):
    def __init__(self, C):
        super().__init__()
        self.gated = TMLP([C, C, C, 1])


def _skew(v):
    z = torch.zeros_like(v[..., 0])
    return torch.stack([
        torch.stack([z, -v[..., 2], v[..., 1]], dim=-1),
        torch.stack([v[..., 2], z, -v[..., 0]], dim=-1),
        torch.stack([-v[..., 1], v[..., 0], z], dim=-1),
    ], dim=-2)


def _decomp(X):
    tr = torch.einsum("...ii->...", X)[..., None, None]
    eye = torch.eye(3, dtype=X.dtype)
    I = tr / 3.0 * eye
    A = 0.5 * (X - X.transpose(-1, -2))
    Sx = 0.5 * (X + X.transpose(-1, -2)) - I
    return I, A, Sx


def _tnorm(X):
    return (X * X).sum(dim=(-2, -1))


def _cmix(lin, comp):
    return lin(comp.permute(0, 2, 3, 1)).permute(0, 3, 1, 2)


class TTensorNet(nn.Module):
    def __init__(self, S, C, R, NL, cutoff):
        super().__init__()
        self.C, self.R, self.rc = C, R, cutoff
        self.tensor_embedding = TTensorEmbedding(S, C, R)
        self.layers = nn.ModuleList([TInteraction(C, R) for _ in range(NL)])
        self.out_norm = nn.LayerNorm(3 * C)
        self.linear = nn.Linear(3 * C, C)
        self.final_layer = TReadOut(C)

    def _basis(self, d):
        n = torch.arange(1, self.R + 1, dtype=d.dtype)
        return ((2.0 / self.rc) ** 0.5
                * torch.sin(n * torch.pi * d[:, None] / self.rc) / d[:, None])

    def oracle(self, pos, Z):
        n = len(Z)
        with torch.no_grad():
            d0 = torch.cdist(pos, pos)
        src, dst = [], []
        for i in range(n):
            for j in range(n):
                if i != j and d0[i, j] < self.rc:
                    src.append(i)
                    dst.append(j)
        src, dst = torch.tensor(src), torch.tensor(dst)
        vec = pos[dst] - pos[src]
        d = vec.norm(dim=-1)
        rhat = vec / d[:, None]
        env = 0.5 * (torch.cos(torch.pi * d / self.rc) + 1.0)
        rbf = self._basis(d)

        te = self.tensor_embedding
        eye = torch.eye(3, dtype=pos.dtype)
        A_e = _skew(rhat)
        S_e = rhat[:, :, None] * rhat[:, None, :] - eye / 3.0
        z = te.emb(Z)
        Zij = te.emb2(torch.cat([z[src], z[dst]], dim=-1))
        W1 = te.distance_proj1(rbf) * env[:, None]
        W2 = te.distance_proj2(rbf) * env[:, None]
        W3 = te.distance_proj3(rbf) * env[:, None]
        edge_X = Zij[:, :, None, None] * (
            W1[:, :, None, None] * eye
            + W2[:, :, None, None] * A_e[:, None]
            + W3[:, :, None, None] * S_e[:, None])
        X = torch.zeros(n, self.C, 3, 3, dtype=pos.dtype).index_add_(0, dst, edge_X)

        norm = te.init_norm(_tnorm(X))
        for lin in te.linears_scalar:
            norm = torch.nn.functional.silu(lin(norm))
        norm = norm.reshape(n, self.C, 3)
        I, A, Sx = _decomp(X)
        I = _cmix(te.linears_tensor[0], I)
        A = _cmix(te.linears_tensor[1], A)
        Sx = _cmix(te.linears_tensor[2], Sx)
        X = (I * norm[..., 0, None, None] + A * norm[..., 1, None, None]
             + Sx * norm[..., 2, None, None])

        for lay in self.layers:
            f = rbf
            for lin in lay.linears_scalar:
                f = torch.nn.functional.silu(lin(f))
            f = (f * env[:, None]).reshape(-1, self.C, 3)
            X = X / (_tnorm(X) + 1.0)[..., None, None]
            I, A, Sx = _decomp(X)
            I = _cmix(lay.linears_tensor[0], I)
            A = _cmix(lay.linears_tensor[1], A)
            Sx = _cmix(lay.linears_tensor[2], Sx)
            Y = I + A + Sx
            msg = (f[:, :, 0, None, None] * I[src]
                   + f[:, :, 1, None, None] * A[src]
                   + f[:, :, 2, None, None] * Sx[src])
            M = torch.zeros_like(Y).index_add_(0, dst, msg)
            B = torch.matmul(Y, M) + torch.matmul(M, Y)
            I, A, Sx = _decomp(B)
            np1 = (_tnorm(B) + 1.0)[..., None, None]
            I = _cmix(lay.linears_tensor[3], I / np1)
            A = _cmix(lay.linears_tensor[4], A / np1)
            Sx = _cmix(lay.linears_tensor[5], Sx / np1)
            dX = I + A + Sx
            X = X + dX + torch.matmul(dX, dX)

        I, A, Sx = _decomp(X)
        inv = torch.cat([_tnorm(I), _tnorm(A), _tnorm(Sx)], dim=-1)
        x = self.linear(self.out_norm(inv))
        return self.final_layer.gated(x)[:, 0].sum()


@pytest.fixture(scope="module")
def converted():
    torch.manual_seed(1)
    torch.set_default_dtype(torch.float64)
    try:
        tm = TTensorNet(S, C, R, NL, CUT)
    finally:
        torch.set_default_dtype(torch.float32)
    sd = {k: v.detach().numpy() for k, v in tm.state_dict().items()}
    cfg = TensorNetConfig(num_species=S, units=C, num_rbf=R, num_layers=NL,
                          cutoff=CUT)
    model = TensorNet(cfg)
    params = model.init(jax.random.PRNGKey(0))
    params = jax.tree.map(lambda x: np.asarray(x, np.float64), params)
    params, report = from_torch("tensornet", sd, params, model=model)
    return tm, model, params, report


def test_zero_unmapped(converted):
    _, _, _, report = converted
    assert report["unused_torch"] == []
    assert report["mapped"] >= 40


def test_energy_force_parity_vs_torch_oracle(converted):
    tm, model, params, _ = converted
    rng = np.random.default_rng(11)
    while True:
        pos_np = rng.uniform(-2.0, 2.0, (8, 3))
        dm = np.linalg.norm(pos_np[:, None] - pos_np[None], axis=-1)
        off = dm[~np.eye(8, dtype=bool)]
        if off.min() > 0.9 and np.abs(off - CUT).min() > 0.05:
            break
    pos_np = pos_np + 10.0
    Z = rng.integers(0, S, 8)

    pos_t = torch.tensor(pos_np, dtype=torch.float64, requires_grad=True)
    e_t = tm.oracle(pos_t, torch.tensor(Z))
    e_t.backward()
    f_t = -pos_t.grad.numpy()

    jax.config.update("jax_enable_x64", True)
    try:
        e_j, f_j, _ = run_potential(
            model.energy_fn, params, pos_np, np.eye(3) * 20.0,
            Z.astype(np.int32), CUT, 1, compute_stress=False,
            dtype=np.float64,
        )
    finally:
        jax.config.update("jax_enable_x64", False)

    assert np.abs(f_t).max() > 1e-4  # non-degeneracy
    np.testing.assert_allclose(e_j, float(e_t.detach()), rtol=1e-9, atol=1e-9)
    np.testing.assert_allclose(f_j, f_t, rtol=1e-7, atol=1e-10)


def test_matpes_shaped_dict_converts():
    """Full-size layout (89 species, 64 channels, 32 rbf, 2 layers) with
    bessel-frequency buffers present: zero unmapped."""
    torch.set_default_dtype(torch.float64)
    try:
        tm = TTensorNet(89, 64, 32, 2, 5.0)
    finally:
        torch.set_default_dtype(torch.float32)
    sd = {k: v.detach().numpy() for k, v in tm.state_dict().items()}
    sd["bond_expansion.rbf.frequencies"] = np.pi * np.arange(1, 33)
    cfg = TensorNetConfig(num_species=89, units=64, num_rbf=32, num_layers=2,
                          cutoff=5.0)
    model = TensorNet(cfg)
    params, report = from_torch("tensornet", sd,
                                model.init(jax.random.PRNGKey(1)), model=model)
    assert report["unused_torch"] == []

    bad = {k: v for k, v in sd.items()}
    bad["bond_expansion.rbf.frequencies"] = np.pi * np.arange(1, 33) * 1.1
    with pytest.raises(ValueError, match="frequencies"):
        from_torch("tensornet", bad, model.init(jax.random.PRNGKey(1)),
                   model=model)
