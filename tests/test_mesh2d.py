"""2-D mesh GSPMD placements: batch-parallel x graph-parallel on one mesh.

The exactness contract under test: for EVERY placement of a packed batch on
the named ``Mesh(("batch", "spatial"))`` — pure batch-parallel (B, 1), the
1-D spatial ring (1, S), and the mixed (B, S) case where each packed
structure is itself spatially partitioned with halo exchange on the spatial
axis — per-structure energies/forces/stresses (/magmoms) match the
single-device reference to fp32 roundoff, for all four model families.

The communication contract: the batch axis carries ZERO collectives at any
placement, and the spatial-axis ppermute count of the packed (B, S) program
equals the 1-D graph-parallel ring's at P=S (packing adds structures, not
communication). Asserted at the jaxpr level via the per-axis collective
attribution (parallel/audit.py) and the ``tools/halo_audit.py --mesh``
gate.
"""

import numpy as np
import pytest

import jax

from distmlip_tpu import geometry
from distmlip_tpu.calculators import Atoms, BatchedPotential, DistPotential
from distmlip_tpu.models import PairConfig, PairPotential
from distmlip_tpu.parallel import (BATCH_AXIS, SPATIAL_AXIS, device_mesh,
                                   graph_mesh, make_batched_potential_fn,
                                   mesh_shape)
from distmlip_tpu.parallel.audit import (axis_collective_count,
                                         collectives_by_axis)
from distmlip_tpu.partition import BucketPolicy, bucket_key, pack_structures

pytestmark = pytest.mark.mesh2d

# (batch_parts, spatial_parts) placements exercised on the 8-CPU-device
# conftest mesh; (4, 2) uses all 8 devices
PLACEMENTS = [(4, 1), (1, 2), (4, 2)]


def make_structure(rng, reps=(4, 1, 1), a=3.5, noise=0.05, n_species=2,
                   species_lo=0):
    """Perturbed fcc supercell wide enough along x to slab into S=2 parts
    at cutoff 3.2 (slab rule: extent / S > 2 * cutoff)."""
    unit = np.array([[0, 0, 0], [0.5, 0.5, 0], [0.5, 0, 0.5], [0, 0.5, 0.5]])
    frac, lattice = geometry.make_supercell(unit, np.eye(3) * a, reps)
    cart = geometry.frac_to_cart(frac, lattice) + rng.normal(
        0, noise, (len(frac), 3))
    z = rng.integers(species_lo, species_lo + n_species,
                     len(frac)).astype(np.int32)
    return Atoms(numbers=z, positions=cart, cell=lattice)


def mesh_batch(rng):
    """4 structures with different sizes, cells and species populations —
    every one spatially partitionable into 2 slabs."""
    return [
        make_structure(rng, reps=(4, 1, 1)),
        make_structure(rng, reps=(4, 2, 1), a=3.7, species_lo=1),
        make_structure(rng, reps=(5, 1, 1), a=3.4),
        make_structure(rng, reps=(4, 1, 1), a=3.6, n_species=3),
    ]


def assert_placements_match_single(model, params, structs, rng,
                                   placements=PLACEMENTS,
                                   compute_magmom=False, atol_f=5e-5,
                                   rtol_e=5e-6):
    sp = DistPotential(model, params, num_partitions=1,
                       compute_magmom=compute_magmom)
    refs = [sp.calculate(a) for a in structs]
    for bp_parts, sp_parts in placements:
        mesh = device_mesh(bp_parts, sp_parts)
        pot = BatchedPotential(model, params, mesh=mesh,
                               compute_magmom=compute_magmom)
        res = pot.calculate(structs)
        assert len(res) == len(structs)
        for b, ref in enumerate(refs):
            scale = max(1.0, abs(ref["energy"]))
            assert abs(res[b]["energy"] - ref["energy"]) < rtol_e * scale, (
                f"placement {bp_parts}x{sp_parts} structure {b}: "
                f"E {res[b]['energy']} vs {ref['energy']}")
            np.testing.assert_allclose(
                res[b]["forces"], ref["forces"], atol=atol_f,
                err_msg=f"placement {bp_parts}x{sp_parts} structure {b}")
            np.testing.assert_allclose(
                res[b]["stress"], ref["stress"], atol=atol_f,
                err_msg=f"placement {bp_parts}x{sp_parts} structure {b}")
            if compute_magmom:
                np.testing.assert_allclose(
                    res[b]["magmoms"], ref["magmoms"], atol=atol_f,
                    err_msg=f"placement {bp_parts}x{sp_parts} structure {b}")


def _pair_model():
    model = PairPotential(PairConfig(cutoff=3.2, kind="lj"))
    return model, model.init()


# ---------------------------------------------------------------------------
# packing invariants at the (B, S) placement
# ---------------------------------------------------------------------------


@pytest.mark.tier1
def test_mesh_pack_invariants(rng):
    structs = mesh_batch(rng)
    graph, host = pack_structures(structs, cutoff=3.2,
                                  spatial_parts=2, batch_parts=4)
    assert graph.num_partitions == 8
    assert graph.spatial_parts == 2 and graph.spatial_size == 2
    assert graph.batch_parts == 4
    assert graph.batch_size == 1           # 1 structure slot per shard
    assert host.per_shard == 1
    assert "_m4x2" in bucket_key(graph)
    # per partition: owned-row struct_id nondecreasing, halo/pad rows carry
    # the sentinel, and every real edge stays inside one structure block
    sid = np.asarray(graph.struct_id)
    owned = np.asarray(graph.owned_mask)
    nmask = np.asarray(graph.node_mask)
    for p in range(graph.num_partitions):
        s_own = sid[p][owned[p]]
        assert np.all(np.diff(s_own) >= 0)
        assert np.all(sid[p][~nmask[p]] == graph.batch_size)
        halo = nmask[p] & ~owned[p]
        assert np.all(sid[p][halo] == graph.batch_size)
        # packed edge_dst stays sorted per partition (unsplit layout)
        assert np.all(np.diff(np.asarray(graph.edge_dst[p])) >= 0)
    # round trip: positions scatter/gather is the identity on owned rows
    pos = host.scatter_positions([a.positions for a in structs],
                                 dtype=np.float64)
    back = host.gather_per_structure(pos)
    for b, atoms in enumerate(structs):
        np.testing.assert_allclose(back[b], atoms.positions)
    # flat slot mapping covers each structure exactly once
    slots = host.structure_slots
    assert len(set(slots.tolist())) == len(structs)
    stats = host.stats
    assert stats["mesh_shape"] == [4, 2]
    assert stats["spatial_parts"] == 2 and stats["batch_parts"] == 4
    assert stats["batch_slots"] == 4


@pytest.mark.tier1
def test_mesh_pack_empty_shards(rng):
    """B < batch_parts leaves trailing shards empty — the placement still
    packs, runs and reads zeros for the empty slots."""
    structs = mesh_batch(rng)[:2]
    graph, host = pack_structures(structs, cutoff=3.2,
                                  spatial_parts=2, batch_parts=4)
    assert graph.num_partitions == 8
    model, params = _pair_model()
    mesh = device_mesh(4, 2)
    pot = make_batched_potential_fn(model.energy_fn, mesh=mesh)
    out = pot(params, jax.device_put(graph), graph.positions)
    energies = np.asarray(out["energies"])
    # slots of the two real structures are finite; all others exactly 0
    real = set(host.structure_slots.tolist())
    for slot in range(graph.batch_parts * graph.batch_size):
        if slot not in real:
            assert energies[slot] == 0.0


# ---------------------------------------------------------------------------
# parity across placements, all four model families
# ---------------------------------------------------------------------------


@pytest.mark.tier1
def test_mesh_parity_pair(rng):
    model, params = _pair_model()
    assert_placements_match_single(model, params, mesh_batch(rng), rng)


@pytest.mark.tier1
def test_mesh_parity_chgnet_with_magmoms(rng):
    from distmlip_tpu.models.chgnet import CHGNet, CHGNetConfig

    cfg = CHGNetConfig(num_species=4, units=16, num_rbf=6, num_angle=4,
                       num_blocks=2, cutoff=3.2, bond_cutoff=2.6)
    model = CHGNet(cfg)
    params = model.init(jax.random.PRNGKey(0))
    assert_placements_match_single(model, params, mesh_batch(rng), rng,
                                   compute_magmom=True)


@pytest.mark.tier1
def test_mesh_parity_tensornet(rng):
    from distmlip_tpu.models.tensornet import TensorNet, TensorNetConfig

    model = TensorNet(TensorNetConfig(num_species=4, units=16, num_rbf=8,
                                      num_layers=2, cutoff=3.2))
    params = model.init(jax.random.PRNGKey(0))
    assert_placements_match_single(model, params, mesh_batch(rng), rng)


def test_mesh_parity_mace(rng):
    from distmlip_tpu.models import MACE, MACEConfig

    model = MACE(MACEConfig(
        num_species=4, channels=16, l_max=2, a_lmax=2, hidden_lmax=1,
        correlation=3, num_interactions=2, num_bessel=6, radial_mlp=16,
        cutoff=3.2, avg_num_neighbors=12.0))
    params = model.init(jax.random.PRNGKey(0))
    assert_placements_match_single(model, params, mesh_batch(rng), rng)


def test_mesh_parity_escn(rng):
    """eSCN's MOLE gate is the one non-block-diagonal piece: at (B, S) the
    per-structure composition pool must psum over the spatial ring."""
    from distmlip_tpu.models import ESCN, ESCNConfig

    model = ESCN(ESCNConfig(num_species=4, channels=16, l_max=2,
                            num_layers=2, num_bessel=6, num_experts=4,
                            cutoff=3.2, avg_num_neighbors=12.0))
    params = model.init(jax.random.PRNGKey(0))
    assert_placements_match_single(model, params, mesh_batch(rng), rng)


# ---------------------------------------------------------------------------
# communication contract: batch axis silent, spatial matches the 1-D ring
# ---------------------------------------------------------------------------


@pytest.mark.tier1
def test_zero_batch_axis_collectives(rng):
    model, params = _pair_model()
    structs = mesh_batch(rng)
    spatial_pp = {}
    for bp_parts, sp_parts in PLACEMENTS:
        mesh = device_mesh(bp_parts, sp_parts)
        graph, _ = pack_structures(structs, cutoff=3.2,
                                   spatial_parts=sp_parts,
                                   batch_parts=bp_parts)
        pot = make_batched_potential_fn(model.energy_fn, mesh=mesh)
        jaxpr = jax.make_jaxpr(pot)(params, graph, graph.positions)
        assert axis_collective_count(jaxpr, BATCH_AXIS) == 0, (
            f"{bp_parts}x{sp_parts}: batch axis must be silent, got "
            f"{collectives_by_axis(jaxpr)}")
        by_axis = collectives_by_axis(jaxpr)
        spatial_pp[(bp_parts, sp_parts)] = by_axis.get(
            SPATIAL_AXIS, {}).get("ppermute", 0)
    # no halo traffic at S=1; identical ring traffic at S=2 whatever B is
    assert spatial_pp[(4, 1)] == 0
    assert spatial_pp[(4, 2)] == spatial_pp[(1, 2)] > 0


@pytest.mark.tier1
def test_halo_audit_mesh_flag():
    import tools.halo_audit as ha

    rc = ha.main(["--model", "pair", "--mesh", "2,2", "--json"])
    assert rc == 0


# ---------------------------------------------------------------------------
# BatchedPotential on a mesh: skin cache, bucket telemetry
# ---------------------------------------------------------------------------


@pytest.mark.tier1
def test_mesh_batched_potential_skin_reuse(rng):
    model, params = _pair_model()
    pot = BatchedPotential(model, params, skin=0.6, mesh=device_mesh(2, 2))
    structs = mesh_batch(rng)
    res0 = pot.calculate(structs)
    assert pot.rebuild_count == 1
    for a in structs:
        a.positions += rng.normal(0, 0.01, a.positions.shape)
    pot.calculate(structs)
    assert pot.rebuild_count == 1  # reused: positions-only upload
    structs[0].positions += 0.5
    pot.calculate(structs)
    assert pot.rebuild_count == 2
    assert pot.last_stats["mesh_shape"] == [2, 2]
    assert pot.last_stats["batch_slots"] == 4
    assert "_m2x2" in pot.last_bucket_key
    # skin-cache hit results stay exact (envelope zeroes skin edges)
    sp = DistPotential(model, params, num_partitions=1)
    for b, atoms in enumerate(structs):
        ref = sp.calculate(atoms)
        res = pot.calculate(structs)[b]
        assert abs(res["energy"] - ref["energy"]) < 5e-6 * max(
            1.0, abs(ref["energy"]))
    assert res0 is not None


# ---------------------------------------------------------------------------
# serving: oversized requests route to the spatial axis of the same mesh
# ---------------------------------------------------------------------------


@pytest.mark.tier1
def test_serve_engine_routes_oversized_to_spatial_axis(rng):
    from distmlip_tpu.serve import ServeEngine
    from distmlip_tpu.telemetry import Telemetry
    from distmlip_tpu.telemetry.sinks import AggregatingSink

    class CaptureSink:
        def __init__(self):
            self.records = []

        def emit(self, rec):
            self.records.append(rec)

        def close(self):
            pass

    model, params = _pair_model()
    small = [make_structure(rng, reps=(2, 1, 1)) for _ in range(3)]
    big = make_structure(rng, reps=(5, 2, 2))
    cap = CaptureSink()
    tel = Telemetry([AggregatingSink(), cap])
    engine = ServeEngine(
        BatchedPotential(model, params, mesh=device_mesh(4, 2)),
        max_batch=4, max_wait_s=0.005,
        max_batch_atoms=len(big) - 1, telemetry=tel)
    futures = [engine.submit(a) for a in small + [big]]
    assert engine.drain(timeout=120)
    results = [f.result(timeout=60) for f in futures]
    # the spatial lane was built from the shared mesh (no explicit fallback)
    assert engine.fallback is None
    lane = engine._spatial_lane
    assert lane is not None and lane.num_partitions == 2
    assert mesh_shape(lane.mesh) == (1, 2)
    engine.close()
    # close() releases the engine-owned lane deterministically
    assert engine._spatial_lane is None
    assert engine.stats.fallback_requests == 1
    # parity on both routes
    sp = DistPotential(model, params, num_partitions=1)
    for atoms, res in zip(small + [big], results):
        ref = sp.calculate(atoms)
        assert abs(res["energy"] - ref["energy"]) < 5e-5 * max(
            1.0, abs(ref["energy"]))
        np.testing.assert_allclose(res["forces"], ref["forces"], atol=5e-5)
    # unified stats emission: the fallback record carries graph stats now
    fb = [r for r in cap.records if r.kind == "serve_fallback"]
    assert len(fb) == 1
    assert fb[0].n_atoms == len(big)
    assert fb[0].num_partitions == 2      # spatial lane at S=2
    batch_recs = [r for r in cap.records if r.kind == "serve_batch"]
    assert batch_recs and batch_recs[0].mesh_shape == [4, 2]


# ---------------------------------------------------------------------------
# telemetry: mesh fields in records + report rendering
# ---------------------------------------------------------------------------


@pytest.mark.tier1
def test_mesh_telemetry_fields_and_report(rng, tmp_path):
    from distmlip_tpu.telemetry import JsonlSink, Telemetry
    from distmlip_tpu.telemetry.report import aggregate, read_jsonl

    path = str(tmp_path / "mesh.jsonl")
    tel = Telemetry([JsonlSink(path)])
    model, params = _pair_model()
    pot = BatchedPotential(model, params, mesh=device_mesh(2, 2),
                           telemetry=tel)
    structs = mesh_batch(rng)
    pot.calculate(structs)
    tel.close()
    records = read_jsonl(path)
    assert len(records) == 1
    rec = records[0]
    assert rec.mesh_shape == [2, 2]
    assert rec.spatial_parts == 2 and rec.batch_parts == 2
    assert rec.halo_send_per_part and len(rec.halo_send_per_part) == 4
    rep = aggregate(records)
    assert rep.counters["mesh_placements"] == [[2, 2]]
    assert "mesh placement (batch x spatial): 2x2" in rep.render()


def test_spatial_halo_imbalance_flagged_per_axis():
    """A skewed spatial ring flags; legitimately different batch rows with
    balanced rings do NOT (the per-axis attribution satellite)."""
    from distmlip_tpu.telemetry import StepRecord
    from distmlip_tpu.telemetry.report import aggregate

    balanced_rows = StepRecord(
        step=1, kind="batched_calculate", spatial_parts=2, batch_parts=2,
        mesh_shape=[2, 2],
        # batch rows differ 10x, but each spatial ring is balanced
        halo_send_per_part=[100, 100, 10, 10])
    assert balanced_rows.spatial_halo_imbalance() == pytest.approx(1.0)
    skewed_ring = StepRecord(
        step=2, kind="batched_calculate", spatial_parts=2, batch_parts=2,
        mesh_shape=[2, 2],
        halo_send_per_part=[100, 10, 50, 50])
    assert skewed_ring.spatial_halo_imbalance() > 1.5
    rep = aggregate([balanced_rows, skewed_ring], imbalance_factor=1.5)
    kinds = [a.kind for a in rep.anomalies]
    assert kinds.count("spatial_halo_imbalance") == 1
    rep_ok = aggregate([balanced_rows], imbalance_factor=1.5)
    assert not [a for a in rep_ok.anomalies
                if "imbalance" in a.kind]
