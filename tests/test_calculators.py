"""Simulation-layer tests: DistPotential pipeline, MD ensembles, relaxation."""

import numpy as np
import pytest

from distmlip_tpu import geometry
from distmlip_tpu.calculators import (
    Atoms,
    DistPotential,
    MolecularDynamics,
    Relaxer,
    TrajectoryObserver,
)
from distmlip_tpu.calculators.md import ENSEMBLES
from distmlip_tpu.models import PairConfig, PairPotential


def make_atoms(rng, reps=(3, 3, 3), a=3.8, noise=0.03):
    unit = np.array([[0, 0, 0], [0.5, 0.5, 0], [0.5, 0, 0.5], [0, 0.5, 0.5]])
    frac, lattice = geometry.make_supercell(unit, np.eye(3) * a, reps)
    cart = geometry.frac_to_cart(frac, lattice) + rng.normal(0, noise, (len(frac), 3))
    return Atoms(numbers=np.full(len(cart), 14), positions=cart, cell=lattice)


@pytest.fixture(scope="module")
def potential():
    model = PairPotential(PairConfig(cutoff=3.5, kind="lj"))
    params = model.init()
    params = {"eps": params["eps"] * 0.1, "sigma": params["sigma"]}
    return DistPotential(model, params, num_partitions=2, compute_stress=True)


def test_calculate_basic(rng, potential):
    atoms = make_atoms(rng)
    res = potential.calculate(atoms)
    assert np.isfinite(res["energy"])
    assert res["forces"].shape == (len(atoms), 3)
    assert res["stress"].shape == (3, 3)
    assert potential.last_timings["device_s"] > 0


def test_partition_report(rng, potential):
    rep = potential.partition_report(make_atoms(rng))
    assert "partition 0" in rep and "partition 1" in rep


def test_nve_conserves_energy(rng, potential):
    atoms = make_atoms(rng)
    atoms.set_maxwell_boltzmann_velocities(300.0, rng=rng)
    md = MolecularDynamics(atoms, potential, ensemble="nve", timestep=1.0)
    e0 = md.results["energy"] + atoms.kinetic_energy()
    md.run(50)
    e1 = md.results["energy"] + atoms.kinetic_energy()
    assert abs(e1 - e0) < 5e-3 * len(atoms) ** 0.5  # drift bound


@pytest.mark.parametrize(
    "ensemble", [e for e in ENSEMBLES if e != "nve"]
)
def test_ensembles_run_and_thermostat(rng, ensemble, potential):
    atoms = make_atoms(rng)
    atoms.set_maxwell_boltzmann_velocities(600.0, rng=rng)
    md = MolecularDynamics(
        atoms, potential, ensemble=ensemble, timestep=1.0,
        temperature=300.0, taut=50.0, seed=1,
    )
    md.run(30)
    assert np.isfinite(md.results["energy"])
    assert np.all(np.isfinite(atoms.positions))
    # thermostatted runs should pull T from 600 toward 300
    if ensemble.startswith("nvt"):
        assert atoms.temperature() < 650.0


def test_trajectory_observer(rng, potential, tmp_path):
    atoms = make_atoms(rng)
    obs = TrajectoryObserver(atoms)
    md = MolecularDynamics(
        atoms, potential, ensemble="nvt_berendsen", trajectory=obs,
        logfile=str(tmp_path / "md.log"), loginterval=2,
    )
    md.run(10)
    assert len(obs.energies) == 5
    obs.save(str(tmp_path / "traj.npz"))
    data = np.load(tmp_path / "traj.npz")
    assert data["positions"].shape[0] == 5
    assert (tmp_path / "md.log").read_text().count("\n") == 5


def test_relaxer_reduces_forces(rng, potential):
    atoms = make_atoms(rng, noise=0.15)
    res0 = potential.calculate(atoms)
    relaxer = Relaxer(potential, fmax=0.05)
    out = relaxer.relax(atoms, steps=200)
    assert out.converged
    assert np.abs(out.forces).max() < 0.05
    assert out.energy < res0["energy"]


def test_relaxer_with_cell(rng, potential):
    atoms = make_atoms(rng, noise=0.05)
    atoms.cell *= 1.03  # slightly strained
    atoms.positions *= 1.03
    relaxer = Relaxer(potential, relax_cell=True, fmax=0.08, smax=0.01)
    out = relaxer.relax(atoms, steps=300)
    assert np.abs(out.forces).max() < 0.08
    # stress reduced vs initial
    res0 = potential.calculate(atoms)
    assert np.abs(out.stress).max() <= np.abs(res0["stress"]).max() + 1e-6


def test_skin_reuse_exact_and_invalidation(rng):
    """skin>0: cache-hit results match rebuild-every-step exactly; cache
    invalidates on displacement > skin/2, cell change, and species change."""
    model = PairPotential(PairConfig(cutoff=3.0, kind="lj"))
    params = {"eps": np.float32(0.1), "sigma": np.float32(2.0)}
    atoms = make_atoms(rng, reps=(4, 3, 3))
    pot0 = DistPotential(model, params, num_partitions=2, skin=0.0)
    pot1 = DistPotential(model, params, num_partitions=2, skin=0.6)
    pos = atoms.positions.copy()
    for _ in range(6):
        pos += rng.normal(0, 0.01, pos.shape)
        a = Atoms(numbers=atoms.numbers, positions=pos, cell=atoms.cell)
        r0 = pot0.calculate(a)
        r1 = pot1.calculate(a)
        assert abs(r0["energy"] - r1["energy"]) < 1e-4
        np.testing.assert_allclose(r0["forces"], r1["forces"], atol=1e-5)
        np.testing.assert_allclose(r0["stress"], r1["stress"], atol=1e-6)
    assert pot1.rebuild_count == 1 and pot0.rebuild_count == 6

    # displacement invalidation: move one atom by > skin/2
    pos2 = pos.copy()
    pos2[0] += [0.4, 0, 0]
    pot1.calculate(Atoms(numbers=atoms.numbers, positions=pos2, cell=atoms.cell))
    assert pot1.rebuild_count == 2

    # cell invalidation: tiny (1e-5 relative) cell change must rebuild
    pot1.calculate(Atoms(numbers=atoms.numbers, positions=pos2,
                         cell=atoms.cell * (1 + 1e-5)))
    assert pot1.rebuild_count == 3


def test_async_rebuild_overlap_matches_sync(rng):
    """The background-prefetched graph must give the same results as
    synchronous rebuilds, and rebuilds during a drifting MD-like run must
    actually be absorbed by the prefetch (prefetch_hits > 0) so the
    rebuild step costs a positions scatter, not a host rebuild
    (VERDICT r4 item 7 — the reference's serial section, pes.py:68-85)."""
    model = PairPotential(PairConfig(cutoff=3.0, kind="lj"))
    params = {"eps": np.float32(0.1), "sigma": np.float32(2.0)}
    atoms = make_atoms(rng, reps=(4, 3, 3))
    pot_async = DistPotential(model, params, num_partitions=2, skin=0.4,
                              async_rebuild=True)
    pot_sync = DistPotential(model, params, num_partitions=2, skin=0.4,
                             async_rebuild=False)
    pos = atoms.positions.copy()
    drift = rng.normal(0, 1.0, pos.shape)
    drift /= np.linalg.norm(drift, axis=1, keepdims=True)
    for _ in range(24):
        pos += 0.02 * drift + rng.normal(0, 0.003, pos.shape)
        a = Atoms(numbers=atoms.numbers, positions=pos, cell=atoms.cell)
        ra = pot_async.calculate(a)
        rs = pot_sync.calculate(a)
        assert abs(ra["energy"] - rs["energy"]) < 1e-4
        np.testing.assert_allclose(ra["forces"], rs["forces"], atol=1e-5)
    assert pot_async.prefetch_hits >= 1, (
        pot_async.prefetch_hits, pot_async.rebuild_count)
    # adoption staleness: a jump far past the prefetch budget must fall
    # back to a fresh build, never serve a stale graph
    pos2 = pos + 5.0
    ra = pot_async.calculate(
        Atoms(numbers=atoms.numbers, positions=pos2, cell=atoms.cell))
    rs = pot_sync.calculate(
        Atoms(numbers=atoms.numbers, positions=pos2, cell=atoms.cell))
    assert abs(ra["energy"] - rs["energy"]) < 1e-4


def test_npt_requires_stress(rng):
    model = PairPotential(PairConfig(cutoff=3.0))
    pot = DistPotential(model, {"eps": np.float32(0.1), "sigma": np.float32(2.0)},
                        num_partitions=1, compute_stress=False)
    atoms = make_atoms(rng, reps=(2, 2, 2))
    with pytest.raises(ValueError, match="compute_stress"):
        MolecularDynamics(atoms, pot, ensemble="npt_berendsen")


def test_ensemble_potential(rng):
    model = PairPotential(PairConfig(cutoff=3.0))
    from distmlip_tpu.calculators import EnsemblePotential

    plist = [{"eps": np.float32(0.1 * (1 + 0.1 * i)), "sigma": np.float32(2.0)}
             for i in range(3)]
    ens = EnsemblePotential(model, plist, num_partitions=2)
    atoms = make_atoms(rng, reps=(2, 2, 2))
    res = ens.calculate(atoms)
    assert res["energies"].shape == (3,)
    assert res["energy_var"] > 0
    assert res["forces"].shape == (len(atoms), 3)
    np.testing.assert_allclose(res["energy"], res["energies"].mean())


@pytest.mark.parametrize("optimizer", ["lbfgs", "bfgs", "mdmin", "cg"])
def test_relaxer_optimizers_converge(rng, potential, optimizer):
    """Every optimizer in the enum (reference ase.py:40-50 analogue) must
    drive the same perturbed crystal below fmax."""
    atoms = make_atoms(rng, noise=0.12)
    out = Relaxer(potential, optimizer=optimizer, fmax=0.05).relax(
        atoms, steps=300)
    assert out.converged and np.abs(out.forces).max() < 0.05


def test_relaxer_optimizers_on_sheared_cell(potential):
    """Convergence on a non-trivial (sheared triclinic) cell for every
    optimizer (VERDICT r3 weak 7). The 0.1-eps LJ landscape is glassy, so
    optimizers may legitimately stop in different basins — the contract is
    convergence below fmax with the energy strictly improved, not basin
    identity."""
    rng = np.random.default_rng(42)
    unit = np.array([[0, 0, 0], [0.5, 0.5, 0], [0.5, 0, 0.5], [0, 0.5, 0.5]])
    lattice0 = np.eye(3) * 3.8
    lattice0[0, 1] = 0.45  # non-trivial (sheared) cell
    frac, lattice = geometry.make_supercell(unit, lattice0, (3, 3, 3))
    cart = geometry.frac_to_cart(frac, lattice) + rng.normal(
        0, 0.07, (len(frac), 3))
    atoms0 = Atoms(numbers=np.full(len(cart), 14), positions=cart.copy(),
                   cell=lattice.copy())
    e0 = potential.calculate(atoms0)["energy"]
    for opt in ("fire", "lbfgs", "bfgs", "mdmin", "cg"):
        atoms = Atoms(numbers=np.full(len(cart), 14), positions=cart.copy(),
                      cell=lattice.copy())
        out = Relaxer(potential, optimizer=opt, fmax=0.05).relax(
            atoms, steps=500)
        assert out.converged, opt
        assert np.abs(out.forces).max() < 0.05, opt
        assert out.energy < e0, (opt, out.energy, e0)


def test_relaxer_exp_cell_filter(rng, potential):
    """Exp cell filter (ASE ExpCellFilter analogue): strained cell relaxes
    with the exponential-map parameterization, reducing the stress."""
    atoms = make_atoms(rng, noise=0.05)
    atoms.cell *= 1.03
    atoms.positions *= 1.03
    res0 = potential.calculate(atoms)
    out = Relaxer(potential, relax_cell=True, cell_filter="exp", fmax=0.08,
                  smax=0.01).relax(atoms, steps=300)
    assert np.abs(out.forces).max() < 0.08
    assert np.abs(out.stress).max() <= np.abs(res0["stress"]).max() + 1e-6


def test_auto_partitioning_clamps_to_slab_rule(rng):
    """Default num_partitions=None: all devices, clamped so the planner's
    slab rule holds for the first structure — a small box must not crash
    with PartitionError on the default constructor (review r4 finding)."""
    import jax

    model = PairPotential(PairConfig(cutoff=4.0))
    params = model.init(jax.random.PRNGKey(0))
    unit = np.array([[0, 0, 0], [0.5, 0.5, 0], [0.5, 0, 0.5], [0, 0.5, 0.5]])
    frac, lattice = geometry.make_supercell(unit, np.eye(3) * 4.5, (4, 4, 4))
    cart = geometry.frac_to_cart(frac, lattice) + rng.normal(0, 0.05, (len(frac), 3))
    atoms = Atoms(numbers=np.full(len(cart), 1), positions=cart, cell=lattice)
    pot = DistPotential(model, params, skin=0.3)  # AUTO on an 8-device mesh
    res = pot.calculate(atoms)
    # 18 A box, 2*(4.0+0.3) = 8.6 -> P clamped to 2, not 8
    assert pot.num_partitions == 2
    assert np.isfinite(res["energy"])
    # stacked ensemble under AUTO must also construct + run (lazy vmap)
    from distmlip_tpu.calculators import EnsemblePotential

    ens = EnsemblePotential(model, [params, params], skin=0.3)
    out = ens.calculate(atoms)
    assert np.isfinite(out["energy"]) and out["energies"].shape == (2,)
    # vacuum-padded slab: only periodic axes count
    atoms_vac = Atoms(numbers=np.full(len(cart), 1), positions=cart,
                      cell=lattice @ np.diag([1.0, 1.0, 4.0]),
                      pbc=[1, 1, 0])
    pot_vac = DistPotential(model, params, skin=0.3)
    pot_vac.ensure_runtime(atoms_vac)
    assert pot_vac.num_partitions == 2  # clamp from the 18 A periodic axes


def test_relaxer_rejects_unknown_optimizer(potential):
    with pytest.raises(ValueError):
        Relaxer(potential, optimizer="nope")
    with pytest.raises(ValueError):
        Relaxer(potential, cell_filter="nope")


def test_stacked_ensemble_matches_sequential(rng):
    """Single-partition ensembles evaluate all members in one vmapped
    program; results must equal the sequential path."""
    import jax

    from distmlip_tpu.calculators import Atoms, EnsemblePotential
    from distmlip_tpu.models import TensorNet, TensorNetConfig

    cfg = TensorNetConfig(num_species=8, units=16, num_rbf=6, num_layers=1,
                          cutoff=3.2)
    model = TensorNet(cfg)
    plist = [model.init(jax.random.PRNGKey(i)) for i in range(3)]
    cart, lattice, species, _ = __import__("tests.conftest", fromlist=["random_cell"]).random_cell(
        rng, n_atoms=24, box=8.0)
    atoms = Atoms(numbers=species + 1, positions=cart, cell=lattice)
    stacked = EnsemblePotential(model, plist, num_partitions=1, stacked=True)
    seq = EnsemblePotential(model, plist, num_partitions=1, stacked=False)
    r1 = stacked.calculate(atoms)
    r2 = seq.calculate(atoms)
    assert abs(r1["energy"] - r2["energy"]) < 1e-5
    np.testing.assert_allclose(r1["forces"], r2["forces"], atol=1e-5)
    np.testing.assert_allclose(r1["energy_var"], r2["energy_var"], rtol=1e-4,
                               atol=1e-8)


@pytest.mark.slow
def test_stacked_ensemble_matches_sequential_multipartition(rng):
    """Multi-partition ensembles also run as ONE vmapped sharded program
    (the vmap batches the whole shard_map'd graph-parallel step); results
    must equal sequential members at P=2."""
    import jax

    from distmlip_tpu.calculators import Atoms, EnsemblePotential
    from distmlip_tpu.models import TensorNet, TensorNetConfig
    from tests.utils import make_crystal

    cfg = TensorNetConfig(num_species=4, units=16, num_rbf=6, num_layers=1,
                          cutoff=3.2)
    model = TensorNet(cfg)
    plist = [model.init(jax.random.PRNGKey(i)) for i in range(3)]
    cart, lattice, species = make_crystal(rng, reps=(5, 3, 3))
    atoms = Atoms(numbers=species + 1, positions=cart, cell=lattice)
    stacked = EnsemblePotential(model, plist, num_partitions=2)
    assert stacked.stacked  # vmap path is now the multi-partition default
    seq = EnsemblePotential(model, plist, num_partitions=2, stacked=False)
    r1 = stacked.calculate(atoms)
    r2 = seq.calculate(atoms)
    assert abs(r1["energy"] - r2["energy"]) < 1e-5
    np.testing.assert_allclose(r1["forces"], r2["forces"], atol=1e-5)
    np.testing.assert_allclose(r1["energy_var"], r2["energy_var"], rtol=1e-4,
                               atol=1e-8)


def test_uma_predictor_task_routing(rng):
    """UMAPredictor: task name routes the dataset conditioning; different
    tasks give different energies on the same structure."""
    import jax

    from distmlip_tpu.calculators import Atoms, UMAPredictor
    from distmlip_tpu.models import ESCN, ESCNConfig

    cfg = ESCNConfig(num_species=8, channels=8, l_max=1, num_layers=1,
                     num_bessel=4, cutoff=3.2)
    model = ESCN(cfg)
    params = model.init(jax.random.PRNGKey(0))
    cart, lattice, species, _ = __import__("tests.conftest", fromlist=["random_cell"]).random_cell(
        rng, n_atoms=20, box=8.0)
    atoms = Atoms(numbers=species + 1, positions=cart, cell=lattice)
    e_omat = UMAPredictor(model, params, task_name="omat",
                          num_partitions=1).calculate(atoms)["energy"]
    e_oc20 = UMAPredictor(model, params, task_name="oc20",
                          num_partitions=1).calculate(atoms)["energy"]
    assert abs(e_omat - e_oc20) > 1e-7
    # explicit atoms.info dataset wins over the task default
    atoms2 = atoms.copy()
    atoms2.info["dataset"] = 2
    e_override = UMAPredictor(model, params, task_name="omat",
                              num_partitions=1).calculate(atoms2)["energy"]
    assert abs(e_override - e_oc20) < 1e-6


def test_out_of_range_system_scalars_raise(rng):
    """Charge/spin/dataset outside the embedding tables must raise instead of
    silently clipping onto the table edge."""
    import jax

    import pytest as _pytest

    from distmlip_tpu.calculators import Atoms, DistPotential
    from distmlip_tpu.models import ESCN, ESCNConfig

    cfg = ESCNConfig(num_species=8, channels=8, l_max=1, num_layers=1,
                     num_bessel=4, cutoff=3.2)
    model = ESCN(cfg)
    params = model.init(jax.random.PRNGKey(0))
    cart, lattice, species, _ = __import__("tests.conftest", fromlist=["random_cell"]).random_cell(
        rng, n_atoms=12, box=8.0)
    pot = DistPotential(model, params, num_partitions=1,
                        species_map=np.arange(0, 10, dtype=np.int32) - 1)
    atoms = Atoms(numbers=species + 1, positions=cart, cell=lattice,
                  info={"charge": 99})
    with _pytest.raises(ValueError, match="charge"):
        pot.calculate(atoms)
    atoms.info = {"dataset": 7}
    with _pytest.raises(ValueError, match="dataset"):
        pot.calculate(atoms)


def test_bfloat16_one_call_switch(rng):
    """DistPotential(compute_dtype='bfloat16') runs end to end; energies and
    forces stay close to fp32 (characterizes the bf16 error)."""
    import jax

    from distmlip_tpu.calculators import Atoms, DistPotential
    from distmlip_tpu.models import MACE, MACEConfig

    cfg = MACEConfig(num_species=8, channels=16, l_max=2, a_lmax=2,
                     hidden_lmax=1, correlation=3, num_interactions=2,
                     num_bessel=6, radial_mlp=16, cutoff=3.2,
                     avg_num_neighbors=12.0)
    model = MACE(cfg)
    params = model.init(jax.random.PRNGKey(0))
    from tests.utils import make_crystal

    cart, lattice, species = make_crystal(rng, reps=(3, 3, 3), n_species=8)
    atoms = Atoms(numbers=species + 1, positions=cart, cell=lattice)
    smap = np.arange(0, 10, dtype=np.int32) - 1

    r32 = DistPotential(model, params, num_partitions=1,
                        species_map=smap).calculate(atoms)
    r16 = DistPotential(model, params, num_partitions=1, species_map=smap,
                        compute_dtype="bfloat16").calculate(atoms)
    n = len(atoms)
    de_per_atom = abs(r16["energy"] - r32["energy"]) / n
    f_scale = max(np.abs(r32["forces"]).max(), 1e-3)
    df_rel = np.abs(r16["forces"] - r32["forces"]).max() / f_scale
    print(f"bf16 vs fp32: dE={de_per_atom:.2e} eV/atom, "
          f"dF_rel={df_rel:.2e}")
    assert de_per_atom < 5e-3
    assert df_rel < 0.1


def test_compute_dtype_guards(rng):
    """Unsupported models must reject compute_dtype loudly; the global
    set_compute_dtype switch routes into supporting models."""
    import jax

    import distmlip_tpu
    import pytest as _pytest

    from distmlip_tpu.calculators import DistPotential
    from distmlip_tpu.models import PairConfig, PairPotential, TensorNet, TensorNetConfig

    # PairPotential has no compute-dtype support: must reject loudly
    pair = PairPotential(PairConfig(cutoff=3.0))
    with _pytest.raises(ValueError, match="compute"):
        DistPotential(pair, pair.init(), num_partitions=1,
                      compute_dtype="bfloat16")
    model = TensorNet(TensorNetConfig(num_species=4, units=8, num_rbf=4,
                                      num_layers=1))
    params = model.init(jax.random.PRNGKey(0))
    # global switch is ignored (without error) for unsupported models...
    distmlip_tpu.set_compute_dtype("bfloat16")
    try:
        pot_pair = DistPotential(pair, pair.init(), num_partitions=1)
        assert pot_pair.model is pair  # untouched: switch ignored
        # ...and picked up by supporting ones (TensorNet included now)
        pot_tn = DistPotential(model, params, num_partitions=1)
        assert pot_tn.model.cfg.dtype == "bfloat16"
        from distmlip_tpu.models import MACE, MACEConfig

        m = MACE(MACEConfig(num_species=4, channels=8, l_max=1, a_lmax=1,
                            hidden_lmax=1, correlation=2, num_interactions=1,
                            num_bessel=4, radial_mlp=8))
        pot = DistPotential(m, m.init(jax.random.PRNGKey(0)), num_partitions=1)
        assert pot.model.cfg.dtype == "bfloat16"
    finally:
        distmlip_tpu.set_compute_dtype("float32")


def test_device_md_matches_host_md(rng):
    """The device-resident MD loop must reproduce host-driven velocity
    Verlet (same skin-reuse graph, same integrator) and conserve energy."""
    from distmlip_tpu.calculators import (Atoms, DeviceMD, DistPotential,
                                          MolecularDynamics)
    from distmlip_tpu.models import PairConfig, PairPotential

    model = PairPotential(PairConfig(cutoff=3.0, kind="lj"))
    params = {"eps": np.float32(0.05), "sigma": np.float32(2.0)}
    atoms_a = make_atoms(rng, reps=(3, 3, 3), noise=0.03)
    atoms_a.set_maxwell_boltzmann_velocities(300.0,
                                             rng=np.random.default_rng(7))
    atoms_b = atoms_a.copy()

    pot_a = DistPotential(model, params, num_partitions=2, skin=1.0)
    dmd = DeviceMD(pot_a, atoms_a, timestep=1.0)
    dmd.run(25)
    assert dmd.steps_done == 25

    pot_b = DistPotential(model, params, num_partitions=2, skin=1.0)
    hmd = MolecularDynamics(atoms_b, pot_b, ensemble="nve", timestep=1.0)
    hmd.run(25)

    np.testing.assert_allclose(atoms_a.positions, atoms_b.positions,
                               atol=2e-4)
    np.testing.assert_allclose(atoms_a.velocities, atoms_b.velocities,
                               atol=2e-4)
    assert np.isfinite(dmd.results["energy"])


def test_device_md_warm_cache_drift_budget(rng):
    """A skin cache warmed by calculate() at *drifted* positions must not
    double-spend the drift budget: DeviceMD charges drift against the
    graph-BUILD positions, so the trajectory matches a cold-start run."""
    from distmlip_tpu.calculators import (Atoms, DeviceMD, DistPotential,
                                          MolecularDynamics)
    from distmlip_tpu.models import PairConfig, PairPotential

    model = PairPotential(PairConfig(cutoff=3.0, kind="lj"))
    params = {"eps": np.float32(0.05), "sigma": np.float32(2.0)}
    atoms = make_atoms(rng, reps=(3, 3, 3), noise=0.03)
    pot = DistPotential(model, params, num_partitions=2, skin=0.5)
    # warm the cache, then drift atoms close to the skin/2 validity edge
    # WITHOUT re-calculating (cache still "valid" but nearly spent)
    pot.calculate(atoms)
    atoms.positions = atoms.positions + 0.23 / np.sqrt(3)
    atoms.set_maxwell_boltzmann_velocities(300.0,
                                           rng=np.random.default_rng(9))
    atoms_cold = atoms.copy()

    dmd = DeviceMD(pot, atoms, timestep=1.0)
    dmd.run(20)
    assert dmd.steps_done == 20

    pot_cold = DistPotential(model, params, num_partitions=2, skin=0.5)
    hmd = MolecularDynamics(atoms_cold, pot_cold, ensemble="nve",
                            timestep=1.0)
    hmd.run(20)
    np.testing.assert_allclose(atoms.positions, atoms_cold.positions,
                               atol=2e-4)


def test_device_md_thermostat_and_rebuild(rng):
    """Berendsen NVT on device pulls T toward the target; a small skin
    forces mid-run rebuilds and the step count still completes."""
    from distmlip_tpu.calculators import Atoms, DeviceMD, DistPotential

    from distmlip_tpu.models import PairConfig, PairPotential

    model = PairPotential(PairConfig(cutoff=3.0, kind="lj"))
    params = {"eps": np.float32(0.05), "sigma": np.float32(2.0)}
    atoms = make_atoms(rng, reps=(3, 3, 3), noise=0.03)
    atoms.set_maxwell_boltzmann_velocities(600.0,
                                           rng=np.random.default_rng(8))
    pot = DistPotential(model, params, num_partitions=2, skin=0.3)
    dmd = DeviceMD(pot, atoms, timestep=1.0, temperature=300.0, taut=25.0)
    dmd.run(60)
    assert dmd.steps_done == 60
    assert dmd.rebuilds >= 1
    assert atoms.temperature() < 650.0


@pytest.mark.parametrize("family", ["tensornet", "chgnet"])
def test_bfloat16_switch_tensornet_chgnet(rng, family):
    """bf16 one-call switch for the matgl-family models: runs end to end
    with bounded deviation from fp32."""
    import jax

    from distmlip_tpu.calculators import Atoms, DistPotential
    from distmlip_tpu.models import (CHGNet, CHGNetConfig, TensorNet,
                                     TensorNetConfig)
    from tests.utils import make_crystal

    if family == "tensornet":
        model = TensorNet(TensorNetConfig(num_species=8, units=16, num_rbf=6,
                                          num_layers=2, cutoff=3.4))
    else:
        model = CHGNet(CHGNetConfig(num_species=8, units=16, num_rbf=6,
                                    num_angle=4, num_blocks=2, cutoff=3.4,
                                    bond_cutoff=2.8))
    params = model.init(jax.random.PRNGKey(0))
    cart, lattice, species = make_crystal(rng, reps=(3, 3, 3), n_species=8)
    atoms = Atoms(numbers=species + 1, positions=cart, cell=lattice)
    smap = np.arange(0, 10, dtype=np.int32) - 1
    r32 = DistPotential(model, params, num_partitions=1,
                        species_map=smap).calculate(atoms)
    r16 = DistPotential(model, params, num_partitions=1, species_map=smap,
                        compute_dtype="bfloat16").calculate(atoms)
    de = abs(r16["energy"] - r32["energy"]) / len(atoms)
    f_scale = max(np.abs(r32["forces"]).max(), 1e-3)
    df = np.abs(r16["forces"] - r32["forces"]).max() / f_scale
    assert de < 1e-2, de
    assert df < 0.15, df


def test_relaxer_traj_file(rng, potential, tmp_path):
    """traj_file saves a TrajectoryObserver npz during relaxation (the
    reference Relaxer's traj_file/interval surface)."""
    atoms = make_atoms(rng, noise=0.1)
    path = str(tmp_path / "relax.npz")
    out = Relaxer(potential, fmax=0.05).relax(atoms, steps=100,
                                              traj_file=path, interval=2)
    data = np.load(path)
    assert data["energies"].shape[0] >= 2
    assert data["positions"].shape[1:] == (len(atoms), 3)
    # last recorded energy is the final state's, recorded exactly once
    assert abs(float(data["energies"][-1]) - out.energy) < 1e-8
    if data["energies"].shape[0] >= 2:
        assert not np.array_equal(data["positions"][-1], data["positions"][-2]) \
            or data["energies"][-1] != data["energies"][-2]
    with pytest.raises(ValueError, match="interval"):
        Relaxer(potential).relax(atoms, steps=1, traj_file=path, interval=0)


def test_relaxer_traj_file_nonconverged_has_final_frame(rng, potential,
                                                        tmp_path):
    """A relax that exhausts ``steps`` without converging must still save the
    RETURNED final state as the trajectory's last frame. Regression for
    ADVICE r4: with interval=1 the loop-top record at the last iteration
    captured the PRE-step state and the post-loop record was skipped, so
    energies[-1] != RelaxResult.energy on every non-converged relax."""
    atoms = make_atoms(rng, noise=0.15)
    path = str(tmp_path / "relax_nc.npz")
    out = Relaxer(potential, fmax=1e-9).relax(  # unreachable fmax
        atoms, steps=4, traj_file=path, interval=1)
    assert not out.converged
    data = np.load(path)
    assert abs(float(data["energies"][-1]) - out.energy) < 1e-8
    assert np.allclose(data["positions"][-1], out.atoms.positions)
