"""Serving engine: scheduler assembly, admission control, max-wait timer
(fake clock — no real sleeps beyond 0.1 s), error isolation, drain/close
lifecycle, and the serving telemetry path (concurrent JsonlSink, report
"serving" section).

The acceptance contract under test: a poison request (NaN positions)
fails ONLY its own Future while the rest of its batch returns results
matching the single-structure ``DistPotential`` path; ``drain()`` returns
with the queue empty and every Future resolved; the scheduler thread
survives every failure mode.
"""

import json
import threading
import time

import numpy as np
import pytest

from distmlip_tpu import geometry
from distmlip_tpu.calculators import Atoms, BatchedPotential, DistPotential
from distmlip_tpu.models import PairConfig, PairPotential
from distmlip_tpu.partition import BucketPolicy
from distmlip_tpu.serve import (EngineClosed, ServeEngine, ServeRejected,
                                plan_batch)
from distmlip_tpu.telemetry import JsonlSink, StepRecord, Telemetry

pytestmark = pytest.mark.serve


class FakeClock:
    """Deterministic engine clock: time moves only when the test says so."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture(scope="module")
def pair():
    model = PairPotential(PairConfig(cutoff=4.0))
    return model, model.init()


def make_structure(rng, reps=(1, 1, 1), a=3.5, noise=0.05):
    unit = np.array([[0, 0, 0], [0.5, 0.5, 0], [0.5, 0, 0.5], [0, 0.5, 0.5]])
    frac, lattice = geometry.make_supercell(unit, np.eye(3) * a, reps)
    cart = geometry.frac_to_cart(frac, lattice) + rng.normal(
        0, noise, (len(frac), 3))
    return Atoms(numbers=np.full(len(cart), 14), positions=cart, cell=lattice)


def poison_structure(rng):
    bad = make_structure(rng)
    bad.positions = bad.positions.copy()
    bad.positions[0, 0] = np.nan
    return bad


# ---------------------------------------------------------------------------
# plan_batch (pure assembly logic)
# ---------------------------------------------------------------------------


@pytest.mark.tier1
def test_plan_batch_head_always_taken():
    # a huge head request seeds its own batch — never starved by the
    # occupancy rule
    plan = plan_batch([5000, 4, 4, 4], max_batch=8)
    assert 0 in plan.take
    assert plan.total_atoms >= 5000


@pytest.mark.tier1
def test_plan_batch_same_rung_always_admits():
    # all tiny: everything fits the base rung -> take max_batch in order
    plan = plan_batch([4] * 20, max_batch=8)
    assert plan.take == list(range(8))
    assert plan.node_cap == 128


@pytest.mark.tier1
def test_plan_batch_skips_only_at_slot_boundaries():
    policy = BucketPolicy()
    # seed fills rung 128 exactly at 4 slots; the 5th would climb to 256 at
    # poor occupancy -> skipped, because 4 is a power-of-two slot count
    plan = plan_batch([32, 32, 32, 32, 32, 32], policy, max_batch=8)
    assert plan.take == [0, 1, 2, 3]
    assert plan.skipped  # the rung-degrading candidates were left queued
    assert plan.occupancy == 1.0
    # off a slot boundary the degrading candidate is admitted anyway
    # (finishing the slot bucket beats node padding): 3 x 40 = 120 on rung
    # 128, then 40 -> 160/256 degrades but len=3 is not a power of two
    plan = plan_batch([40, 40, 40, 40], policy, max_batch=8)
    assert 3 in plan.take


@pytest.mark.tier1
def test_plan_batch_respects_max_batch_and_window():
    plan = plan_batch([4] * 100, max_batch=8, window=50)
    assert len(plan.take) == 8
    plan = plan_batch([4] * 100, max_batch=64, window=10)
    assert len(plan.take) == 10


# ---------------------------------------------------------------------------
# engine basics
# ---------------------------------------------------------------------------


@pytest.mark.tier1
def test_single_request_roundtrip(rng, pair):
    model, params = pair
    atoms = make_structure(rng)
    with ServeEngine(BatchedPotential(model, params),
                     max_wait_s=0.005) as engine:
        res = engine.submit(atoms).result(timeout=60)
        ref = DistPotential(model, params, num_partitions=1).calculate(atoms)
        assert abs(res["energy"] - ref["energy"]) < 1e-5
        np.testing.assert_allclose(res["forces"], ref["forces"], atol=5e-5)
        assert engine.stats.completed == 1


@pytest.mark.tier1
def test_staged_queue_assembles_one_full_batch(rng, pair):
    model, params = pair
    engine = ServeEngine(BatchedPotential(model, params), max_batch=8,
                         max_wait_s=0.005, start=False)
    futs = [engine.submit(make_structure(rng)) for _ in range(8)]
    engine.start()
    for f in futs:
        f.result(timeout=60)
    assert engine.drain(timeout=30)
    assert engine.stats.batches == 1          # one micro-batch of 8
    assert engine.stats.completed == 8
    dom = engine.stats.dominant_bucket()
    assert dom is not None and dom[1] == 1.0  # all 8 slots filled
    engine.close()


@pytest.mark.tier1
def test_priority_and_deadline_ordering(rng, pair):
    model, params = pair
    pot = BatchedPotential(model, params)
    clock = FakeClock()
    engine = ServeEngine(pot, max_batch=1, max_wait_s=0.0, start=False,
                         clock=clock)
    order = []
    fut_lo = engine.submit(make_structure(rng), priority=5)
    fut_hi = engine.submit(make_structure(rng), priority=-5)
    # same priority class: earliest deadline first, then FIFO
    fut_d2 = engine.submit(make_structure(rng), priority=0, deadline=200.0)
    fut_d1 = engine.submit(make_structure(rng), priority=0, deadline=100.0)
    for name, f in (("lo", fut_lo), ("hi", fut_hi), ("d2", fut_d2),
                    ("d1", fut_d1)):
        f.add_done_callback(lambda _f, n=name: order.append(n))
    engine.start()
    assert engine.drain(timeout=30)
    engine.close()
    assert order == ["hi", "d1", "d2", "lo"]


@pytest.mark.tier1
def test_max_wait_timer_fake_clock(rng, pair):
    model, params = pair
    clock = FakeClock()
    engine = ServeEngine(BatchedPotential(model, params), max_batch=8,
                         max_wait_s=50.0, clock=clock)
    fut = engine.submit(make_structure(rng))
    time.sleep(0.05)          # real time passes; fake clock is frozen
    assert not fut.done(), "dispatched before the max-wait deadline"
    clock.advance(51.0)       # past max_wait on the engine clock
    engine.kick()
    fut.result(timeout=60)
    assert engine.stats.batches == 1
    engine.close()


@pytest.mark.tier1
def test_deadline_miss_counted_but_result_delivered(rng, pair):
    model, params = pair
    clock = FakeClock()
    engine = ServeEngine(BatchedPotential(model, params), max_batch=8,
                         max_wait_s=0.0, start=False, clock=clock)
    fut = engine.submit(make_structure(rng), deadline=0.5)
    clock.advance(1.0)        # deadline expires while queued
    engine.start()
    res = fut.result(timeout=60)
    assert "energy" in res    # late results are still delivered
    assert engine.drain(timeout=30)
    assert engine.stats.deadline_misses == 1
    engine.close()


@pytest.mark.tier1
def test_properties_filter(rng, pair):
    model, params = pair
    with ServeEngine(BatchedPotential(model, params),
                     max_wait_s=0.005) as engine:
        res = engine.submit(make_structure(rng),
                            properties=("energy", "forces")).result(timeout=60)
    assert set(res) == {"energy", "forces"}


def test_cancelled_future_is_skipped(rng, pair):
    model, params = pair
    engine = ServeEngine(BatchedPotential(model, params), max_batch=8,
                         max_wait_s=0.005, start=False)
    fut = engine.submit(make_structure(rng))
    keep = engine.submit(make_structure(rng))
    assert fut.cancel()
    engine.start()
    keep.result(timeout=60)
    assert engine.drain(timeout=30)
    assert engine.stats.cancelled == 1
    assert engine.stats.completed == 1
    engine.close()


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------


@pytest.mark.tier1
def test_admission_reject(rng, pair):
    model, params = pair
    engine = ServeEngine(BatchedPotential(model, params), max_queue=2,
                         admission="reject", start=False)
    engine.submit(make_structure(rng))
    engine.submit(make_structure(rng))
    with pytest.raises(ServeRejected):
        engine.submit(make_structure(rng))
    assert engine.stats.rejected == 1
    engine.start()
    assert engine.drain(timeout=30)
    assert engine.stats.completed == 2
    engine.close()


@pytest.mark.tier1
def test_admission_block_unblocks_on_dispatch(rng, pair):
    model, params = pair
    engine = ServeEngine(BatchedPotential(model, params), max_queue=1,
                         admission="block", max_wait_s=0.005, start=False)
    f1 = engine.submit(make_structure(rng))
    blocked_fut = []
    done = threading.Event()

    def blocked_submit():
        blocked_fut.append(engine.submit(make_structure(rng)))
        done.set()

    t = threading.Thread(target=blocked_submit, daemon=True)
    t.start()
    assert not done.wait(0.05), "submit should block while the queue is full"
    engine.start()            # scheduler drains the queue, freeing the slot
    assert done.wait(10), "blocked submit never unblocked"
    f1.result(timeout=60)
    blocked_fut[0].result(timeout=60)
    engine.close()


def test_admission_block_raises_on_close(rng, pair):
    model, params = pair
    engine = ServeEngine(BatchedPotential(model, params), max_queue=1,
                         admission="block", start=False)
    engine.submit(make_structure(rng))
    raised = threading.Event()

    def blocked_submit():
        try:
            engine.submit(make_structure(rng))
        except EngineClosed:
            raised.set()

    t = threading.Thread(target=blocked_submit, daemon=True)
    t.start()
    time.sleep(0.05)
    engine.close(drain=False)
    assert raised.wait(10), "blocked submitter not released by close()"


# ---------------------------------------------------------------------------
# error isolation (the acceptance contract)
# ---------------------------------------------------------------------------


@pytest.mark.tier1
def test_poison_request_fails_only_its_own_future(rng, pair):
    model, params = pair
    engine = ServeEngine(BatchedPotential(model, params), max_batch=8,
                         max_wait_s=0.005, start=False)
    goods = [make_structure(rng, reps=r)
             for r in ((1, 1, 1), (2, 1, 1), (2, 2, 1))]
    good_futs = [engine.submit(a) for a in goods]
    bad_fut = engine.submit(poison_structure(rng))
    engine.start()
    with pytest.raises(ValueError, match="non-finite"):
        bad_fut.result(timeout=60)
    sp = DistPotential(model, params, num_partitions=1)
    for atoms, fut in zip(goods, good_futs):
        res = fut.result(timeout=60)
        ref = sp.calculate(atoms)
        # fp32 roundoff parity with the single-structure path
        assert abs(res["energy"] - ref["energy"]) < 1e-5 * max(
            1.0, abs(ref["energy"]))
        np.testing.assert_allclose(res["forces"], ref["forces"], atol=5e-5)
    # engine thread survived: it still serves
    again = engine.submit(goods[0]).result(timeout=60)
    assert "energy" in again
    assert engine.drain(timeout=30)
    assert engine.queue_depth == 0
    assert engine.stats.failed == 1
    assert engine.stats.scheduler_errors == 0
    engine.close()


class _StubPotential:
    """Minimal BatchedPotential surface that raises on any batch containing
    a marked structure — exercises the batch-fault -> singles-retry
    isolation path (the poison screen can't catch this class of fault)."""

    caps = BucketPolicy()
    compile_count = 0
    last_stats: dict = {}

    def __init__(self):
        self.batch_sizes = []

    def attach_telemetry(self, telemetry):
        pass

    def calculate(self, structures):
        self.batch_sizes.append(len(structures))
        if any(a.info.get("poison") for a in structures):
            raise RuntimeError("graph build blew up")
        return [{"energy": float(len(a)), "free_energy": float(len(a))}
                for a in structures]


@pytest.mark.tier1
def test_batch_fault_isolated_by_singles_retry(rng):
    stub = _StubPotential()
    engine = ServeEngine(stub, max_batch=8, max_wait_s=0.005, start=False)
    goods = [make_structure(rng) for _ in range(3)]
    bad = make_structure(rng)
    bad.info["poison"] = True
    good_futs = [engine.submit(a) for a in goods]
    bad_fut = engine.submit(bad)
    engine.start()
    with pytest.raises(RuntimeError, match="blew up"):
        bad_fut.result(timeout=60)
    for f in good_futs:
        assert f.result(timeout=60)["energy"] == float(len(goods[0]))
    assert engine.drain(timeout=30)
    engine.close()
    # one failed batch of 4, then 4 singles
    assert stub.batch_sizes[0] == 4
    assert sorted(stub.batch_sizes[1:]) == [1, 1, 1, 1]
    assert engine.stats.scheduler_errors == 0


# ---------------------------------------------------------------------------
# oversized-structure fallback lane
# ---------------------------------------------------------------------------


@pytest.mark.tier1
def test_oversized_routes_to_fallback(rng, pair):
    model, params = pair
    big = make_structure(rng, reps=(2, 2, 2))   # 32 atoms
    small = make_structure(rng)                 # 4 atoms
    fallback = DistPotential(model, params, num_partitions=1)
    engine = ServeEngine(BatchedPotential(model, params), fallback=fallback,
                         max_batch_atoms=16, max_wait_s=0.005, start=False)
    f_big = engine.submit(big)
    f_small = engine.submit(small)
    engine.start()
    res = f_big.result(timeout=60)
    ref = DistPotential(model, params, num_partitions=1).calculate(big)
    assert abs(res["energy"] - ref["energy"]) < 1e-5 * max(
        1.0, abs(ref["energy"]))
    f_small.result(timeout=60)
    assert engine.drain(timeout=30)
    assert engine.stats.fallback_requests == 1
    engine.close()


def test_oversized_without_fallback_fails_future(rng, pair):
    model, params = pair
    engine = ServeEngine(BatchedPotential(model, params),
                         max_batch_atoms=16, max_wait_s=0.005)
    fut = engine.submit(make_structure(rng, reps=(2, 2, 2)))
    with pytest.raises(ValueError, match="max_batch_atoms"):
        fut.result(timeout=60)
    engine.close()


# ---------------------------------------------------------------------------
# lifecycle: drain / close
# ---------------------------------------------------------------------------


@pytest.mark.tier1
def test_drain_resolves_everything(rng, pair):
    model, params = pair
    engine = ServeEngine(BatchedPotential(model, params), max_batch=4,
                         max_wait_s=10.0)   # long max-wait: drain must flush
    futs = [engine.submit(make_structure(rng)) for _ in range(10)]
    assert engine.drain(timeout=60)
    assert engine.queue_depth == 0
    assert all(f.done() for f in futs)
    engine.close()


@pytest.mark.tier1
def test_close_is_graceful_and_idempotent(rng, pair):
    model, params = pair
    engine = ServeEngine(BatchedPotential(model, params), max_wait_s=10.0)
    futs = [engine.submit(make_structure(rng)) for _ in range(3)]
    engine.close()            # default: drains first
    assert all(f.done() for f in futs)
    engine.close()            # idempotent
    with pytest.raises(EngineClosed):
        engine.submit(make_structure(rng))


def test_close_without_drain_fails_pending(rng, pair):
    model, params = pair
    engine = ServeEngine(BatchedPotential(model, params), max_wait_s=10.0,
                         start=False)
    futs = [engine.submit(make_structure(rng)) for _ in range(3)]
    engine.close(drain=False)
    for f in futs:
        with pytest.raises(EngineClosed):
            f.result(timeout=10)


# ---------------------------------------------------------------------------
# telemetry: serving records, concurrent JSONL, report section
# ---------------------------------------------------------------------------


@pytest.mark.tier1
def test_serve_records_and_report_section(rng, pair, tmp_path):
    from distmlip_tpu.telemetry.report import aggregate, read_jsonl

    model, params = pair
    path = tmp_path / "serve.jsonl"
    tel = Telemetry([JsonlSink(str(path))])
    engine = ServeEngine(BatchedPotential(model, params), max_batch=4,
                         max_wait_s=0.005, telemetry=tel)
    futs = [engine.submit(make_structure(rng)) for _ in range(8)]
    for f in futs:
        f.result(timeout=60)
    engine.drain(timeout=30)
    engine.close()
    tel.close()
    records = read_jsonl(str(path))
    serve_recs = [r for r in records if r.kind == "serve_batch"]
    assert serve_recs, "no serve_batch records emitted"
    for r in serve_recs:
        assert len(r.queue_wait_s) == r.batch_size
        assert len(r.request_latency_s) == r.batch_size
        assert all(w >= 0 for w in r.queue_wait_s)
        assert all(lat >= w for lat, w in zip(r.request_latency_s,
                                              r.queue_wait_s))
        assert 0.0 < r.batch_occupancy <= 1.0
    # batched_calculate records rode the same sink from the same thread
    assert any(r.kind == "batched_calculate" for r in records)
    rep = aggregate(records)
    s = rep.counters["serving"]
    assert s["requests"] == 8
    assert s["rejects"] == 0 and s["deadline_misses"] == 0
    assert s["latency_p99_s"] >= s["latency_p50_s"] >= 0
    assert "serving (ServeEngine):" in rep.render()


@pytest.mark.tier1
def test_jsonl_sink_concurrent_emits_line_atomic(tmp_path):
    path = tmp_path / "concurrent.jsonl"
    sink = JsonlSink(str(path))
    n_threads, per_thread = 8, 50

    def writer(tid):
        for i in range(per_thread):
            sink.emit(StepRecord(step=i, kind=f"t{tid}",
                                 timings={"total_s": 0.001 * i}))

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    sink.close()
    lines = path.read_text().splitlines()
    assert len(lines) == n_threads * per_thread
    kinds = set()
    for line in lines:
        rec = json.loads(line)   # every line parses: no interleaving
        kinds.add(rec["kind"])
    assert kinds == {f"t{t}" for t in range(n_threads)}
    # emit after close: silent no-op
    sink.emit(StepRecord())
